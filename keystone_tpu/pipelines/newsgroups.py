"""NewsgroupsPipeline: text classification with n-grams + Naive Bayes.

Reference: ``pipelines/text/NewsgroupsPipeline.scala:14-75`` — the canonical
``then / thenEstimator / thenLabelEstimator`` chain:

    Trim >> LowerCase >> Tokenizer >> NGrams(1..n) >> TermFrequency(x=>1)
        .then(CommonSparseFeatures(100k)).fit(train)
        .then(NaiveBayes(numClasses)).fit(train, labels)
        >> MaxClassifier

The same composition works here verbatim; the host stages stop at the sparse
vectorizer, after which fit and scoring are single XLA programs over the
padded-COO batch (see ``learning/naive_bayes.py``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np

from keystone_tpu.core.config import parse_config
from keystone_tpu.core.pipeline import chain
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
from keystone_tpu.loaders.newsgroups import load_newsgroups, synthetic_newsgroups
from keystone_tpu.ops.nlp import (
    EncodedCommonSparseFeatures,
    LowerCase,
    NGramsFeaturizer,
    Tokenizer,
    Trim,
)
from keystone_tpu.ops.util import MaxClassifier
from keystone_tpu.ops.util.sparse import CommonSparseFeatures, TermFrequency, binary_weight
from keystone_tpu.utils import Timer, get_logger

logger = get_logger("keystone_tpu.pipelines.newsgroups")


@dataclasses.dataclass
class NewsgroupsConfig:
    train_location: str = ""
    test_location: str = ""
    n_grams: int = 2
    common_features: int = 100000
    nb_lambda: float = 1.0
    synthetic_train: int = 2000
    synthetic_test: int = 500
    synthetic_classes: int = 20
    seed: int = 42
    # Featurize ON DEVICE (ops/nlp/device_text.py): n-gram packing, per-doc
    # term collapse, top-K selection, and COO vectorization as XLA
    # sort/segment programs; the synthetic corpus is generated on device as
    # id tensors (the image pipelines' protocol). Real text still tokenizes/
    # encodes on the host (the documented string frontier). Falls back to
    # the host paths below when vocab x order overflows 63-bit packing.
    device_path: bool = True
    # Fused integer-key host featurization (ops/nlp/fast_text.py): the same
    # features as the tuple chain up to tie-breaks at the top-K truncation
    # cut (exact equivalence below the cut is pinned in tests; both paths
    # break cut ties arbitrarily), at ~10x less host time. False runs the
    # reference-shaped node chain.
    fast_host_path: bool = True


def _run_device(config: NewsgroupsConfig) -> Optional[dict]:
    """The all-device track: id tensors in, error rates out. Returns None
    when the key width cannot pack (callers fall back to the host paths)."""
    from keystone_tpu.loaders.newsgroups import synthetic_newsgroups_device
    from keystone_tpu.ops.nlp import Tokenizer, Trim, LowerCase, WordFrequencyEncoder
    from keystone_tpu.ops.nlp.device_text import DeviceCommonSparseFeatures

    orders = tuple(range(1, config.n_grams + 1))
    if config.train_location:
        # disk IO stays outside the Timer (matching the host paths, which
        # also load before timing); the string->id frontier runs INSIDE it
        # so device-vs-host wall-clocks stay comparable on real corpora
        train_docs, train_labels, class_names = load_newsgroups(config.train_location)
        test_docs, test_labels, _ = load_newsgroups(config.test_location, class_names)
        num_classes = len(class_names)
        gen = None
    else:
        num_classes = config.synthetic_classes
        gen = lambda n, seed: synthetic_newsgroups_device(
            n, num_classes, seed=seed
        )

    results: dict = {}
    with Timer("NewsgroupsPipeline") as total:
        if gen is None:
            tokenize = lambda docs: Tokenizer("[\\s]+")(LowerCase()(Trim()(docs)))
            train_tokens = tokenize(train_docs)
            encoder = WordFrequencyEncoder().fit(train_tokens)
            train_ids, train_len = encoder.encode_padded(train_tokens)
            test_ids, test_len = encoder.encode_padded(tokenize(test_docs))
            vocab_size = encoder.vocab_size
        else:
            train_ids, train_len, train_labels, vocab_size = gen(
                config.synthetic_train, config.seed
            )
            test_ids, test_len, test_labels, _ = gen(
                config.synthetic_test, config.seed + 1
            )
        try:
            est = DeviceCommonSparseFeatures(
                base=vocab_size + 1,
                orders=orders,
                num_features=config.common_features,
                weight="binary",
            )
        except OverflowError as e:
            logger.info("device featurization unavailable (%s); host path", e)
            return None
        vectorizer, train_vecs = est.fit_transform(train_ids, train_len)
        test_vecs = vectorizer.apply_encoded(test_ids, test_len)
        nb = NaiveBayesEstimator(num_classes, config.nb_lambda).fit(
            train_vecs, train_labels
        )
        classifier = nb.then(MaxClassifier())
        evaluator = MulticlassClassifierEvaluator(num_classes)
        train_eval = evaluator(classifier(train_vecs), train_labels)
        test_eval = evaluator(classifier(test_vecs), test_labels)
        results["train_error"] = 100.0 * float(train_eval.total_error)
        results["test_error"] = 100.0 * float(test_eval.total_error)
        results["macro_f1"] = float(test_eval.macro_f1)
    results["num_features"] = vectorizer.num_features
    results["wallclock_s"] = total.elapsed
    logger.info("Train error: %.2f%%", results["train_error"])
    logger.info(
        "Test error: %.2f%%  macro-F1: %.3f",
        results["test_error"], results["macro_f1"],
    )
    return results


def run(config: NewsgroupsConfig) -> dict:
    if config.device_path:
        results = _run_device(config)
        if results is not None:
            return results
    if config.train_location:
        train_docs, train_labels, class_names = load_newsgroups(config.train_location)
        test_docs, test_labels, _ = load_newsgroups(config.test_location, class_names)
    else:
        train_docs, train_labels, class_names = synthetic_newsgroups(
            config.synthetic_train, config.synthetic_classes, seed=config.seed
        )
        test_docs, test_labels, _ = synthetic_newsgroups(
            config.synthetic_test, config.synthetic_classes, seed=config.seed + 1
        )
    num_classes = len(class_names)

    results: dict = {}
    with Timer("NewsgroupsPipeline") as total:
        orders = tuple(range(1, config.n_grams + 1))
        if config.fast_host_path:
            est = EncodedCommonSparseFeatures(
                orders=orders, num_features=config.common_features, weight="binary"
            )
            vectorizer, train_vecs = est.fit_transform(train_docs)
        else:
            featurizer = chain(
                Trim(),
                LowerCase(),
                Tokenizer("[\\s]+"),
                NGramsFeaturizer(orders=orders),
                TermFrequency(fn=binary_weight),  # binary presence (reference x=>1)
            )
            # Same thenEstimator / thenLabelEstimator composition as the
            # reference, but the host-side featurization is materialized once
            # and the downstream stages fit/evaluate on it (the reference's
            # `Cacher` move) — chaining the raw estimators would re-tokenize
            # the corpus once per fit.
            train_feats = featurizer(train_docs)
            sparse_vec = CommonSparseFeatures(config.common_features).fit(train_feats)
            train_vecs = sparse_vec(train_feats)
            vectorizer = featurizer.then(sparse_vec)
        nb = NaiveBayesEstimator(num_classes, config.nb_lambda).fit(
            train_vecs, train_labels
        )
        classifier = nb.then(MaxClassifier())
        predictor = vectorizer.then(classifier)

        evaluator = MulticlassClassifierEvaluator(num_classes)
        train_eval = evaluator(classifier(train_vecs), train_labels)
        test_eval = evaluator(predictor(test_docs), test_labels)

    results["train_error"] = 100.0 * float(train_eval.total_error)
    results["test_error"] = 100.0 * float(test_eval.total_error)
    results["macro_f1"] = float(test_eval.macro_f1)
    results["wallclock_s"] = total.elapsed
    logger.info("Train error: %.2f%%", results["train_error"])
    logger.info("Test error: %.2f%%  macro-F1: %.3f", results["test_error"], results["macro_f1"])
    return results


def main(argv=None):
    config = parse_config(NewsgroupsConfig, argv, prog="NewsgroupsPipeline")
    print(json.dumps(run(config)))


if __name__ == "__main__":
    main()
