"""RandomCifar: random gaussian conv filters → rectify → pool → OLS.

Reference: ``pipelines/images/cifar/RandomCifar.scala:16-109``.
"""

from __future__ import annotations

import dataclasses
import json

import jax
import jax.numpy as jnp

from keystone_tpu.core.config import parse_config
from keystone_tpu.learning import LinearMapEstimator
from keystone_tpu.loaders.cifar import load_cifar_binary, synthetic_cifar_device
from keystone_tpu.pipelines._cifar_conv import conv_featurizer, fit_and_eval
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger

logger = get_logger("keystone_tpu.pipelines.random_cifar")


@dataclasses.dataclass
class RandomCifarConfig:
    train_location: str = ""
    test_location: str = ""
    num_filters: int = 100
    patch_size: int = 6
    pool_size: int = 14
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float = 0.0
    seed: int = 0
    synthetic_train: int = 10000
    synthetic_test: int = 2000


def run(config: RandomCifarConfig) -> dict:
    if config.train_location:
        train = load_cifar_binary(config.train_location)
        test = load_cifar_binary(config.test_location)
    else:
        train = synthetic_cifar_device(config.synthetic_train, seed=1)
        test = synthetic_cifar_device(config.synthetic_test, seed=2)

    with use_mesh(get_mesh()), Timer("RandomCifar.pipeline") as total:
        filters = jax.random.normal(
            jax.random.key(config.seed),
            (config.num_filters, config.patch_size**2 * 3),
            jnp.float32,
        )
        featurizer = conv_featurizer(
            filters, None, config.alpha, config.pool_stride, config.pool_size
        )
        solver = LinearMapEstimator(lam=config.lam or None)
        # conv + doubled-rectifier intermediates per row, f32
        conv_hw = (32 - config.patch_size + 1) ** 2
        per_row = 3 * config.num_filters * conv_hw * 4
        results = fit_and_eval(
            featurizer,
            lambda a, b, m: solver.fit(a, b, mask=m),
            train,
            test,
            per_row_intermediate_bytes=per_row,
        )
    results["wallclock_s"] = total.elapsed
    logger.info(
        "Training error: %.2f%%  Test error: %.2f%%",
        results["train_error"],
        results["test_error"],
    )
    return results


def main(argv=None):
    print(json.dumps(run(parse_config(RandomCifarConfig, argv, prog="RandomCifar"))))


if __name__ == "__main__":
    main()
