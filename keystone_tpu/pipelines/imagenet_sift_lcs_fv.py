"""ImageNetSiftLcsFV: the flagship-scale workload — SIFT+FV and LCS+FV
branches zipped, weighted block coordinate descent, top-5 error.

Reference: ``pipelines/images/imagenet/ImageNetSiftLcsFV.scala:26-271``
(flagship config: blockSize 4096, λ=6e-5, mixtureWeight=0.25, 1e7 PCA/GMM
samples, ``:197-218``).
"""

from __future__ import annotations

import dataclasses
import json

import jax.numpy as jnp

from keystone_tpu.core.config import parse_config
from keystone_tpu.learning.block_weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.loaders.imagenet import (
    IMAGENET_NUM_CLASSES,
    load_imagenet,
    synthetic_imagenet_device,
)
from keystone_tpu.ops.images import GrayScaler, LCSExtractor, SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels, TopKClassifier
from keystone_tpu.pipelines._fisher import fit_fisher_branch
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger
from keystone_tpu.utils.stats import get_err_percent

logger = get_logger("keystone_tpu.pipelines.imagenet_sift_lcs_fv")


@dataclasses.dataclass
class ImageNetSiftLcsFVConfig:
    train_location: str = ""
    train_labels: str = ""
    test_location: str = ""
    test_labels: str = ""
    sift_pca_dim: int = 64
    lcs_pca_dim: int = 64
    vocab_size: int = 16
    num_pca_samples: int = 10000000
    num_gmm_samples: int = 10000000
    lam: float = 6e-5
    mixture_weight: float = 0.25
    block_size: int = 4096
    num_iter: int = 1
    image_hw: int = 256
    lcs_stride: int = 4
    lcs_border: int = 16
    lcs_patch: int = 6
    seed: int = 42
    # synthetic fallback
    synthetic_train: int = 512
    synthetic_test: int = 128
    synthetic_classes: int = 8
    synthetic_hw: int = 96


def run(config: ImageNetSiftLcsFVConfig) -> dict:
    if config.train_location:
        hw = (config.image_hw, config.image_hw)
        train = load_imagenet(config.train_location, config.train_labels, hw)
        test = load_imagenet(config.test_location, config.test_labels, hw)
        num_classes = IMAGENET_NUM_CLASSES
    else:
        hw = (config.synthetic_hw, config.synthetic_hw)
        train = synthetic_imagenet_device(
            config.synthetic_train, config.synthetic_classes, hw, seed=1
        )
        test = synthetic_imagenet_device(
            config.synthetic_test, config.synthetic_classes, hw, seed=2
        )
        num_classes = config.synthetic_classes

    results: dict = {}
    with use_mesh(get_mesh()), Timer("ImageNetSiftLcsFV.pipeline") as total:
        train_imgs = jnp.asarray(train[0])
        test_imgs = jnp.asarray(test[0])
        gray_train = GrayScaler()(train_imgs)[..., 0]
        gray_test = GrayScaler()(test_imgs)[..., 0]

        # SIFT branch: Hellinger on raw descriptors before PCA (:52-53)
        sift_featurizer, sift_train = fit_fisher_branch(
            SIFTExtractor(),
            gray_train,
            config.sift_pca_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            hellinger_first=True,
        )
        # LCS branch on RGB (:96-148)
        lcs_featurizer, lcs_train = fit_fisher_branch(
            LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch),
            train_imgs,
            config.lcs_pca_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed + 7,
        )

        # ZipVectors over the two branches (:179-180)
        train_feats = jnp.concatenate([sift_train, lcs_train], axis=1)
        labels = ClassLabelIndicatorsFromIntLabels(num_classes)(jnp.asarray(train[1]))

        with Timer("fit.block_weighted_least_squares"):
            model = BlockWeightedLeastSquaresEstimator(
                config.block_size, config.num_iter, config.lam, config.mixture_weight
            ).fit(train_feats, labels)

        with Timer("eval.top5"):
            test_feats = jnp.concatenate(
                [sift_featurizer(gray_test), lcs_featurizer(test_imgs)], axis=1
            )
            scores = model(test_feats)
            top5 = TopKClassifier(k=min(5, num_classes))(scores)
            results["test_top5_error"] = get_err_percent(top5, test[1])
            top1 = TopKClassifier(k=1)(scores)
            results["test_top1_error"] = get_err_percent(top1, test[1])

    results["wallclock_s"] = total.elapsed
    logger.info(
        "TEST top-5 error: %.2f%%  top-1: %.2f%%",
        results["test_top5_error"],
        results["test_top1_error"],
    )
    return results


def main(argv=None):
    print(
        json.dumps(
            run(parse_config(ImageNetSiftLcsFVConfig, argv, prog="ImageNetSiftLcsFV"))
        )
    )


if __name__ == "__main__":
    main()
