"""ImageNetSiftLcsFV: the flagship-scale workload — SIFT+FV and LCS+FV
branches zipped, weighted block coordinate descent, top-5 error.

Reference: ``pipelines/images/imagenet/ImageNetSiftLcsFV.scala:26-271``
(flagship config: blockSize 4096, λ=6e-5, mixtureWeight=0.25, 1e7 PCA/GMM
samples, ``:197-218``).
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.config import parse_config
from keystone_tpu.learning.block_weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.loaders.imagenet import (
    IMAGENET_NUM_CLASSES,
    load_imagenet,
    synthetic_imagenet_device,
)
from keystone_tpu.ops.images import GrayScaler, LCSExtractor, SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels, TopKClassifier
from keystone_tpu.pipelines._fisher import fit_fisher_branch
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger
from keystone_tpu.utils.stats import get_err_percent

logger = get_logger("keystone_tpu.pipelines.imagenet_sift_lcs_fv")


@dataclasses.dataclass
class ImageNetSiftLcsFVConfig:
    train_location: str = ""
    train_labels: str = ""
    test_location: str = ""
    test_labels: str = ""
    sift_pca_dim: int = 64
    lcs_pca_dim: int = 64
    vocab_size: int = 16
    num_pca_samples: int = 10000000
    num_gmm_samples: int = 10000000
    lam: float = 6e-5
    mixture_weight: float = 0.25
    # Solver column block size. 0 = auto (core/plan.py precedence: an
    # explicitly-set value here > KEYSTONE_BLOCK_SIZE env > the planner's
    # HBM-budget-safe size under KEYSTONE_OPTIMIZER > the hand-tuned 4096
    # — the _pick_tiles order from PR 7, documented in the README's
    # "Pipeline optimizer" section).
    block_size: int = 0
    num_iter: int = 1
    image_hw: int = 256
    # size-bucketed variable-shape ingest for real archives: comma-separated
    # HxW ladder (e.g. "128x128,256x256") — images land in the smallest
    # containing bucket (pad, no resize), both branches compile once per
    # bucket shape. Works in-core (_run_bucketed) AND with --streaming
    # (_run_streaming_bucketed: per-bucket resident descriptors through the
    # out-of-core solver). Empty -> single frame at image_hw.
    buckets: str = ""
    lcs_stride: int = 4
    lcs_border: int = 16
    lcs_patch: int = 6
    seed: int = 42
    # synthetic fallback
    synthetic_train: int = 512
    synthetic_test: int = 128
    synthetic_classes: int = 8
    synthetic_hw: int = 96
    # prototype-noise stddev for the synthetic generator; at the default
    # (0.08) the classes are cleanly separable, so 0% error is a plumbing
    # check, not a quality claim — raise it for a non-vacuous error bar
    # (BASELINE.md's flagship row states the noise used for its numbers)
    synthetic_noise: float = 0.08
    # Shuffled-label control (flagship quality protocol, BASELINE.md): train
    # labels are drawn independently of the images, so any fitted model's
    # error must collapse to ~chance. A non-trivial error at normal labels
    # plus chance error here is the evidence the quality signal is real.
    shuffle_labels: bool = False
    # Out-of-core (flagship) mode: features re-computed per column block
    # inside the weighted solver instead of materializing the (n, d) matrix
    # (``fit_streaming``; reference regime ImageNetSiftLcsFV.scala:197-218).
    streaming: bool = False
    # Streaming INGEST mode (real archives only): batches flow straight
    # from the bounded decode pipeline (core/ingest.py — parallel tar/JPEG
    # decode into a recycled host buffer ring) into per-batch extraction,
    # so the RAW image tensor never exists on host or device; peak decoded
    # host memory is KEYSTONE_INGEST_BUFFERS × ingest_batch × frame bytes
    # regardless of dataset size (``fit_streaming_ingest``). Implies the
    # out-of-core solver path; incompatible with --buckets and with the
    # gmm_* streaming-experiment knobs.
    ingest: bool = False
    ingest_batch: int = 256  # images per decoded batch = extraction dispatch
    extract_chunk: int = 2048  # images per descriptor-extraction dispatch
    sample_images: int = 4096  # images whose descriptors feed PCA/GMM fits
    fv_row_chunk: int = 1024  # images per FV block-featurization chunk
    desc_dtype: str = "bfloat16"  # resident reduced-descriptor storage
    # FV cache grouping: consecutive solver blocks per shared-posterior
    # featurization pass (0 = recompute per block; -1 = auto). Peak extra
    # HBM = one group's (n, fv_cache_blocks·block_size) features in
    # fv_cache_dtype. Auto resolves to 2 = the HBM-validated flagship
    # configuration (~1.7 GB bf16 group buffer at n=102 400 next to
    # ~6.4 GB resident descriptors on a 16 GB chip; 4-block groups OOM
    # there) — or, under KEYSTONE_OPTIMIZER, to the widest group whose
    # buffer fits a slice of KEYSTONE_HBM_BUDGET
    # (core/plan.py::resolve_cache_blocks; explicit values always win).
    fv_cache_blocks: int = -1
    # Mid-fit checkpoint/resume for the streaming solve: every N completed
    # blocks the solver state lands at solver_checkpoint (atomic); a rerun
    # with the same path resumes bit-exactly from the last boundary
    # (BlockWeightedLeastSquaresEstimator.fit_streaming). Empty/0 = off.
    solver_checkpoint: str = ""
    solver_checkpoint_every: int = 0
    fv_cache_dtype: str = "bfloat16"
    # best-of-n GMM-EM restarts by data log-likelihood (learning/gmm.py).
    # Measured caveat: a higher-likelihood GMM is NOT a more discriminative
    # FV codebook — best-of-4 landed mid-band (top-5 15.3%) while single
    # draws spanned 4.7-16.5% — so the flagship keeps n_init=1 and
    # BASELINE.md reports the band, not a point (the knob remains for
    # density-model uses where likelihood IS the objective)
    gmm_n_init: int = 1
    # >1: fit that many independently-seeded codebooks per branch and keep
    # the one whose normalized FVs CLASSIFY a held-out probe of the sample
    # images best (pipelines/_fisher.py::select_codebook_by_probe).
    # MEASURED (round 4): probe ranking does NOT transfer reliably to the
    # full-scale metric — helps some draws, badly hurts others (evidence in
    # the selector's docstring) — so the default stays 1 (off), like the
    # likelihood-restart knob and for the same reason. Streaming path only.
    gmm_probe_candidates: int = 1
    gmm_probe_images: int = 4096
    gmm_probe_proj_dim: int = 2048
    # External-codebook CONTROL (VERDICT r4 #3 — attribute the flagship
    # quality band): "sklearn" fits each branch codebook with
    # sklearn.mixture.GaussianMixture (diag covariance, k-means++ init —
    # the strongest external initializer) on a host subsample of the SAME
    # reduced-descriptor feed, then runs the UNCHANGED FV+solver path. If
    # the seed band persists under an external EM, the instability is the
    # task's; if sklearn's codebooks are materially stabler, the gap is in
    # learning/gmm.py. Findings: BASELINE.md flagship row. Streaming only.
    gmm_backend: str = "native"
    # host-side sample rows for the sklearn control fit (the full 2M-row
    # device sample would cost minutes of tunnel transfer + hours of
    # single-core EM; the subsample is drawn from the same ColumnSampler
    # output, so both backends see the same descriptor distribution)
    gmm_sklearn_sample: int = 200_000
    gmm_sklearn_max_iter: int = 50
    # FV ensembling (the one untried cheap stabilizer, VERDICT r4 #3):
    # >1 fits that many independently-seeded codebooks of vocab_size/k
    # centers each per branch and CONCATENATES their normalized FV
    # features — total feature dim unchanged, EM variance averaged over
    # k independent draws. Streaming path only.
    gmm_ensemble: int = 1

    def validate(self):
        if self.buckets and not self.train_location:
            raise ValueError(
                "--buckets is variable-size ingest for real archives; the "
                "synthetic generator emits one size (drop --buckets or set "
                "--train-location)"
            )
        if self.gmm_backend not in ("native", "sklearn"):
            raise ValueError(f"gmm_backend {self.gmm_backend!r}: native|sklearn")
        if (self.gmm_backend != "native" or self.gmm_ensemble > 1) and not (
            self.streaming and not self.buckets
        ):
            raise ValueError(
                "gmm_backend/gmm_ensemble are streaming-path experiment "
                "knobs (--streaming, no --buckets); the in-core and "
                "bucketed paths would silently ignore them"
            )
        if self.gmm_ensemble > 1 and self.gmm_probe_candidates > 1:
            raise ValueError(
                "gmm_probe_candidates selects ONE codebook; combining it "
                "with gmm_ensemble would silently skip probe selection"
            )
        if self.ingest:
            if not (self.train_location and self.test_location):
                raise ValueError(
                    "--ingest streams real tar archives (core/ingest.py); "
                    "set --train-location/--test-location (the synthetic "
                    "generator has nothing to decode)"
                )
            if self.buckets:
                raise ValueError(
                    "--ingest decodes into one fixed frame (image_hw); "
                    "combine with --buckets is not supported yet"
                )
            if (self.gmm_backend != "native" or self.gmm_ensemble > 1
                    or self.gmm_probe_candidates > 1):
                raise ValueError(
                    "gmm_backend/gmm_ensemble/gmm_probe_candidates are "
                    "in-core-sample experiment knobs; the --ingest path "
                    "would silently ignore them"
                )



def _resolve_solver_knobs(config: ImageNetSiftLcsFVConfig, n_rows: int,
                          num_classes: int, sub_k: int = 0,
                          fixed_bytes: int = 0) -> ImageNetSiftLcsFVConfig:
    """Concrete solver knobs from the auto sentinels (``block_size=0``,
    ``fv_cache_blocks=-1``) via the whole-pipeline planner
    (``core/plan.py``). Precedence per knob: explicitly-set config value >
    ``KEYSTONE_BLOCK_SIZE`` env > HBM-budget-planned (``KEYSTONE_OPTIMIZER``
    on) > the hand-tuned flagship defaults (4096 / 2-block groups) — so
    with the optimizer off this is the byte-identical prior configuration.

    ``sub_k`` (streaming paths) constrains planned blocks to sizes that
    tile both branches' per-codebook feature layout; ``fixed_bytes`` is
    the resident-descriptor HBM the block solve must coexist with."""
    import math

    from keystone_tpu.core import plan

    pcas = (config.sift_pca_dim, config.lcs_pca_dim)
    quantum = math.lcm(*pcas)
    valid = None
    if sub_k:
        top = min(2 * sub_k * p for p in pcas)
        valid = [
            b for b in range(quantum, top + 1, quantum)
            if all((2 * sub_k) % (b // p) == 0 for p in pcas)
        ]
        if not valid:
            # no planned block can tile BOTH branches' layout at these
            # dims: an empty valid set must not reach resolve_block_size
            # (falsy -> no snap -> an untiled block silently truncates
            # the streaming block loop). Only the PLANNED rung drops out;
            # explicit config and KEYSTONE_BLOCK_SIZE keep their
            # documented precedence, then the hand default — exactly the
            # optimizer-off configuration — and say so.
            from keystone_tpu.utils import knobs as _knobs

            block = (config.block_size
                     or _knobs.get("KEYSTONE_BLOCK_SIZE") or 4096)
            logger.warning(
                "plan: no block size tiles pca dims %s at 2*sub_k=%d; "
                "planning skipped, using %d", pcas, 2 * sub_k, block,
            )
            return dataclasses.replace(
                config, block_size=block,
                fv_cache_blocks=(config.fv_cache_blocks
                                 if config.fv_cache_blocks >= 0 else 2),
            )
    cache_itemsize = jnp.dtype(config.fv_cache_dtype).itemsize
    block = plan.resolve_block_size(
        "imagenet.weighted_solver",
        explicit=config.block_size or None,
        n_rows=n_rows, num_classes=num_classes, default=4096,
        cache_blocks=2, cache_dtype_bytes=cache_itemsize,
        fixed_bytes=fixed_bytes, quantum=quantum,
        ceiling=max(valid) if valid else None, valid=valid,
    )
    cache_blocks = plan.resolve_cache_blocks(
        "imagenet.fv_cache",
        explicit=(config.fv_cache_blocks
                  if config.fv_cache_blocks >= 0 else None),
        n_rows=n_rows, block_size=block, itemsize=cache_itemsize, default=2,
    )
    # the block was sized assuming 2-block groups; a WIDER planned group
    # must not push the combined peak past the budget the block claims to
    # provably fit. Clamp only the PLANNED group width (an explicit
    # fv_cache_blocks is the caller's contract and passes verbatim).
    if config.fv_cache_blocks < 0 and plan.enabled():
        budget = plan.hbm_budget_bytes()
        while budget is not None and cache_blocks > 2 and (
            plan.block_solve_peak_bytes(
                block, n_rows=n_rows, num_classes=num_classes,
                cache_blocks=cache_blocks,
                cache_dtype_bytes=cache_itemsize, fixed_bytes=fixed_bytes,
            ) > budget
        ):
            cache_blocks -= 1
    return dataclasses.replace(
        config, block_size=block, fv_cache_blocks=cache_blocks
    )


def _fit_sklearn_gmm(gmm_sample, k_centers: int, em_seed: int, config):
    """External-codebook control fit (see ``gmm_backend``): sklearn
    diag-covariance EM with k-means++ init on a host subsample of the same
    device sample the native estimator would see. ONE host pull of
    ``gmm_sklearn_sample`` rows (the sampler output is already a uniform
    draw, so a prefix is a uniform subsample)."""
    from sklearn.mixture import GaussianMixture as _SkGMM

    from keystone_tpu.learning.gmm import GaussianMixtureModel

    m = min(config.gmm_sklearn_sample, int(gmm_sample.shape[0]))
    x = np.asarray(gmm_sample[:m], np.float32)
    sk = _SkGMM(
        n_components=k_centers, covariance_type="diag",
        init_params="k-means++", random_state=em_seed,
        max_iter=config.gmm_sklearn_max_iter, reg_covar=1e-4,
    ).fit(x)
    return GaussianMixtureModel(
        means=jnp.asarray(sk.means_, jnp.float32),
        variances=jnp.asarray(sk.covariances_, jnp.float32),
        weights=jnp.asarray(sk.weights_, jnp.float32),
    )


class _ArraySource:
    """Chunk provider over materialized (imgs, labels) arrays."""

    def __init__(self, imgs, labels):
        self.n = int(jnp.asarray(labels).shape[0])
        self._imgs, self._labels = imgs, labels

    def chunk(self, i0: int, i1: int):
        return jnp.asarray(self._imgs[i0:i1]), np.asarray(self._labels[i0:i1])


class _SyntheticSource:
    """Chunk provider that generates images on device per chunk — the whole
    image tensor (e.g. 100k×64²×3 f32 ≈ 4.9 GB) never exists at once. Fixed
    prototype_seed keeps the class structure consistent across chunks.

    ``shuffle_labels=True`` replaces each chunk's labels with fresh uniform
    draws independent of the images — the shuffled-label control run."""

    def __init__(self, n: int, num_classes: int, hw, seed: int,
                 noise: float = 0.08, shuffle_labels: bool = False):
        self.n, self._classes, self._hw, self._seed = n, num_classes, hw, seed
        self._noise = noise
        self._shuffle = shuffle_labels

    def chunk(self, i0: int, i1: int):
        imgs, labels = synthetic_imagenet_device(
            i1 - i0, self._classes, self._hw,
            seed=self._seed * 1000003 + i0, noise=self._noise,
        )
        if self._shuffle:
            rng = np.random.default_rng(self._seed * 7 + i0)
            labels = rng.integers(0, self._classes, size=i1 - i0)
        # labels STAY on device: an np.asarray here would block on the
        # chunk's whole generation — 50 serialized host round trips inside
        # the extraction loop (measured ~5 s of the flagship's wall-clock;
        # consumers pull the concatenated labels once)
        return imgs, jnp.asarray(labels)


def _run_streaming_bucketed(config: ImageNetSiftLcsFVConfig) -> dict:
    """Out-of-core weighted fit over VARIABLE-SIZE real archives: bucketed
    ingest (no global resize) + the streaming solver.

    Each (H, W) bucket of the ladder keeps its own resident bf16
    reduced-descriptor tensors (static shapes per bucket; per-image
    descriptor counts follow ``num_descriptors(bh, bw)``); PCA/GMM fit once
    on samples pooled across buckets; and every solver block is a
    :class:`~keystone_tpu.ops.images.fisher_vector.BucketConcatNode` that
    row-concatenates the bucket featurizations — so
    ``BlockWeightedLeastSquaresEstimator.fit_streaming`` (cache groups,
    Woodbury solves, mid-fit checkpointing) runs unchanged on bucketed
    data. Train and test are BOTH aligned to the full ladder (a bucket a
    split happens not to populate gets a zero-row tensor, shapes from
    ``jax.eval_shape`` — no extraction runs), so the node keys can never
    miss and labels always match featurized rows; the test archive loads
    only at eval time and eval nodes regroup to full-branch cache groups
    under the same 1 GiB gate as the fixed-shape streaming path.
    """
    import jax

    from keystone_tpu.learning.block_linear import streaming_predict
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.learning.pca import PCAEstimator
    from keystone_tpu.loaders.imagenet import load_imagenet_bucketed
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_bucketed_fisher_block_nodes,
    )
    from keystone_tpu.ops.stats import BatchSignedHellingerMapper
    from keystone_tpu.pipelines._fisher import pooled_bucket_sample
    from keystone_tpu.pipelines.voc_sift_fisher import parse_buckets

    ladder = parse_buckets(config.buckets)
    num_classes = IMAGENET_NUM_CLASSES

    sift = SIFTExtractor()
    hellinger = BatchSignedHellingerMapper()
    lcs = LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch)
    dtype = jnp.dtype(config.desc_dtype)

    def desc_shapes(hw):
        """Per-image descriptor shapes for a bucket, WITHOUT computing:
        abstract evaluation of the two branch extractors."""
        spec = jax.ShapeDtypeStruct((1, hw[0], hw[1], 3), jnp.float32)
        s_sh = jax.eval_shape(
            lambda im: hellinger(sift(GrayScaler()(im)[..., 0])), spec
        ).shape
        l_sh = jax.eval_shape(lcs, spec).shape
        return s_sh[1:], l_sh[1:]

    def load_aligned(location, labels_path):
        """Ladder-aligned (hw, imgs, labels) list: every ladder bucket
        present, zero-row entries for buckets this split does not populate."""
        groups = {hw: (imgs, labels) for hw, imgs, labels
                  in load_imagenet_bucketed(location, labels_path, ladder)}
        out = []
        for hw in ladder:
            imgs, labels = groups.get(hw, (
                np.zeros((0, hw[0], hw[1], 3), np.float32),
                np.zeros((0,), np.int32),
            ))
            out.append((hw, imgs, labels))
        return out

    def extract(groups):
        """Per ladder bucket: (sift descs, lcs descs, labels) — chunked by
        extract_chunk within each bucket (one compile per bucket shape);
        zero-row buckets get correctly-shaped empty tensors for free."""
        out = []
        for hw, imgs, labels in groups:
            if imgs.shape[0] == 0:
                (nd_s, d_s), (nd_l, d_l) = desc_shapes(hw)
                sd = jnp.zeros((0, nd_s, d_s), jnp.float32)
                ld = jnp.zeros((0, nd_l, d_l), jnp.float32)
            else:
                from keystone_tpu.core.cache import use_cache as _use_cache
                from keystone_tpu.core.dataset import iter_prefetched_chunks

                sd_parts, ld_parts = [], []
                # chunk t+1's host->device transfer is dispatched ahead
                # while chunk t extracts; the intermediate cache is
                # suppressed per chunk — the descriptors stay resident in
                # this function's own tensors, a cache copy would double
                # them
                for _, part in iter_prefetched_chunks(
                    lambda a, b: jnp.asarray(imgs[a:b]),
                    imgs.shape[0], config.extract_chunk,
                ):
                    with _use_cache(None):
                        sd_parts.append(
                            hellinger(sift(GrayScaler()(part)[..., 0]))
                        )
                        ld_parts.append(lcs(part))
                sd = jnp.concatenate(sd_parts) if len(sd_parts) > 1 else sd_parts[0]
                ld = jnp.concatenate(ld_parts) if len(ld_parts) > 1 else ld_parts[0]
            out.append((hw, sd, ld, labels))
        return out

    results: dict = {}
    with use_mesh(get_mesh()), Timer("ImageNetSiftLcsFV.streaming") as total:
        train = load_aligned(config.train_location, config.train_labels)
        bucket_counts = {
            f"{hw[0]}x{hw[1]}": int(imgs.shape[0]) for hw, imgs, _ in train
        }
        tr = extract(train)
        del train  # raw images are not needed past extraction

        with Timer("streaming.fit_pca_gmm"):
            sample_s = pooled_bucket_sample(
                [sd for _, sd, _, _ in tr], config.num_pca_samples, config.seed
            )
            pca_s = PCAEstimator(config.sift_pca_dim).fit_batch(sample_s)
            gmm_s = GaussianMixtureModelEstimator(
                config.vocab_size, n_init=config.gmm_n_init
            ).fit(pooled_bucket_sample(
                [pca_s(sd) for _, sd, _, _ in tr],
                config.num_gmm_samples, config.seed + 1,
            ))
            sample_l = pooled_bucket_sample(
                [ld for _, _, ld, _ in tr], config.num_pca_samples,
                config.seed + 7,
            )
            pca_l = PCAEstimator(config.lcs_pca_dim).fit_batch(sample_l)
            gmm_l = GaussianMixtureModelEstimator(
                config.vocab_size, n_init=config.gmm_n_init
            ).fit(pooled_bucket_sample(
                [pca_l(ld) for _, _, ld, _ in tr],
                config.num_gmm_samples, config.seed + 8,
            ))
            del sample_s, sample_l

        def reduce_groups(groups_ex):
            raw, lbl_parts = {}, []
            for i, (hw, sd, ld, labels) in enumerate(groups_ex):
                rs = pca_s(sd).astype(dtype)
                rl = pca_l(ld).astype(dtype)
                raw[f"sift_b{i}"] = rs
                raw[f"l1_sift_b{i}"] = fisher_l1_norms(
                    rs, gmm_s, config.fv_row_chunk
                )
                raw[f"lcs_b{i}"] = rl
                raw[f"l1_lcs_b{i}"] = fisher_l1_norms(
                    rl, gmm_l, config.fv_row_chunk
                )
                lbl_parts.append(labels)
            return raw, np.concatenate(lbl_parts)

        with Timer("streaming.reduce_train"):
            raw_train, train_labels = reduce_groups(tr)
        del tr

        # planner-derived solver knobs (explicit config/env values win —
        # see _resolve_solver_knobs): the resident reduced descriptors are
        # the fixed HBM term the block solve must fit next to
        config = _resolve_solver_knobs(
            config, int(train_labels.shape[0]), num_classes,
            sub_k=config.vocab_size,
            fixed_bytes=sum(v.nbytes for v in raw_train.values()),
        )
        bidx = list(range(len(ladder)))
        blocks_s = 2 * config.vocab_size // (
            config.block_size // config.sift_pca_dim
        )
        blocks_l = 2 * config.vocab_size // (
            config.block_size // config.lcs_pca_dim
        )

        def make_nodes(cache_s, cache_l):
            return make_bucketed_fisher_block_nodes(
                gmm_s, config.block_size,
                [(f"sift_b{i}", f"l1_sift_b{i}") for i in bidx],
                row_chunk=config.fv_row_chunk, cache_blocks=cache_s,
            ) + make_bucketed_fisher_block_nodes(
                gmm_l, config.block_size,
                [(f"lcs_b{i}", f"l1_lcs_b{i}") for i in bidx],
                row_chunk=config.fv_row_chunk, cache_blocks=cache_l,
            )

        nodes = make_nodes(config.fv_cache_blocks, config.fv_cache_blocks)
        cache_dtype = (
            jnp.dtype(config.fv_cache_dtype) if config.fv_cache_blocks else None
        )
        labels_ind = ClassLabelIndicatorsFromIntLabels(num_classes)(
            jnp.asarray(train_labels)
        )
        with Timer("fit.block_weighted_least_squares_streaming"):
            model = BlockWeightedLeastSquaresEstimator(
                config.block_size, config.num_iter, config.lam,
                config.mixture_weight,
            ).fit_streaming(
                nodes, raw_train, labels_ind, cache_dtype=cache_dtype,
                checkpoint_path=config.solver_checkpoint or None,
                checkpoint_every=config.solver_checkpoint_every,
            )
        del raw_train

        with Timer("eval.top5_streaming"):
            # test archive loads only now — nothing test-side was resident
            # through the memory-critical solve
            raw_test, test_labels = reduce_groups(
                extract(load_aligned(config.test_location, config.test_labels))
            )
            eval_nodes = nodes
            if config.fv_cache_blocks:
                n_test = int(test_labels.shape[0])
                item = cache_dtype.itemsize
                budget = 1 << 30  # per-branch group-buffer cap (as _run_streaming)

                def eval_cache(blocks: int) -> int:
                    bytes_ = n_test * blocks * config.block_size * item
                    return blocks if bytes_ < budget else config.fv_cache_blocks

                eval_nodes = make_nodes(
                    eval_cache(blocks_s), eval_cache(blocks_l)
                )
            scores = streaming_predict(model, eval_nodes, raw_test, cache_dtype)
            top5 = TopKClassifier(k=min(5, num_classes))(scores)
            results["test_top5_error"] = get_err_percent(top5, test_labels)
            top1 = TopKClassifier(k=1)(scores)
            results["test_top1_error"] = get_err_percent(top1, test_labels)

    results["buckets"] = bucket_counts
    results["wallclock_s"] = total.elapsed
    results["feature_dim"] = 2 * (
        config.sift_pca_dim + config.lcs_pca_dim
    ) * config.vocab_size
    logger.info(
        "bucketed streaming TEST top-5: %.2f%%  top-1: %.2f%%  buckets: %s",
        results["test_top5_error"], results["test_top1_error"],
        results["buckets"],
    )
    return results


def _run_streaming(config: ImageNetSiftLcsFVConfig, train_src, test_src,
                   num_classes: int) -> dict:
    """Flagship out-of-core path: chunked extraction → PCA/GMM on a sample →
    resident reduced descriptors (bf16) → weighted BCD with per-block FV
    re-featurization. HBM arithmetic in
    ``BlockWeightedLeastSquaresEstimator`` docstring."""
    import jax

    from keystone_tpu.learning.block_linear import streaming_predict
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.learning.pca import PCAEstimator
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )
    from keystone_tpu.ops.stats import BatchSignedHellingerMapper, ColumnSampler

    results: dict = {}
    chunk = config.extract_chunk
    sift = SIFTExtractor()
    hellinger = BatchSignedHellingerMapper()
    lcs = LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch)

    def sift_descs(imgs):
        # Hellinger on raw descriptors before PCA (:52-53)
        return hellinger(sift(GrayScaler()(imgs)[..., 0]))

    def lcs_descs(imgs):
        return lcs(imgs)

    with use_mesh(get_mesh()), Timer("ImageNetSiftLcsFV.streaming") as total:
        # Pass A: descriptor sample → PCA + GMM per branch. The reference
        # samples 1e7 descriptors from the full train set
        # (ImageNetSiftLcsFV.scala:206-213); here the sample pool is the
        # first ``sample_images`` images' descriptors (chunked extraction
        # cannot revisit all images twice for free), then the same
        # ColumnSampler seeds as the in-core path.
        # Sample bound rounded up to a chunk boundary (capped at n) so pass-A
        # chunk keys line up exactly with reduce_split's — a ragged final
        # sample chunk would miss the cache AND pin its descriptors for the
        # whole memory-critical solve.
        n_sample = min(-(-min(config.sample_images, train_src.n) // chunk) * chunk,
                       train_src.n)
        # Raw descriptor chunks from pass A are kept (keyed by chunk bounds,
        # labels included) so reduce_split below never re-extracts — or even
        # re-generates/transfers — the sample images.
        desc_cache: dict = {}
        s_parts, l_parts, lbl_parts = [], [], []
        from keystone_tpu.core.prefetch import prefetch_map

        sample_bounds = [
            (i0, min(i0 + chunk, train_src.n))
            for i0 in range(0, n_sample, chunk)
        ]
        # chunk t+1's host→device transfer / generation dispatch overlaps
        # chunk t's extraction (the same double buffer as reduce_split)
        chunk_feed = prefetch_map(
            lambda b: train_src.chunk(*b), sample_bounds
        )
        from keystone_tpu.core.cache import use_cache as _use_cache

        for (i0, i1), (imgs, lbls) in zip(sample_bounds, chunk_feed):
            # desc_cache below is the pipeline's own memo for these chunks;
            # letting the intermediate cache store them TOO would hold a
            # second multi-GB copy of every sample chunk
            with _use_cache(None):
                sd, ld = sift_descs(imgs), lcs_descs(imgs)
            desc_cache[(i0, i1)] = (sd, ld, lbls)
            s_parts.append(sd)
            l_parts.append(ld)
            lbl_parts.append(lbls)
        sample_s = jnp.concatenate(s_parts) if len(s_parts) > 1 else s_parts[0]
        sample_l = jnp.concatenate(l_parts) if len(l_parts) > 1 else l_parts[0]
        if config.gmm_probe_candidates > 1:
            # device concat + ONE host pull, and only when the probe
            # selector (the sole consumer) is actually on
            sample_lbls = np.asarray(
                jnp.concatenate([jnp.asarray(l) for l in lbl_parts])
            )
        else:
            sample_lbls = None
        del s_parts, l_parts, lbl_parts

        ens = max(1, config.gmm_ensemble)
        if config.vocab_size % ens:
            raise ValueError(
                f"gmm_ensemble {ens} must divide vocab_size "
                f"{config.vocab_size}"
            )
        sub_k = config.vocab_size // ens

        with Timer("streaming.fit_pca_gmm"):

            def fit_branch(sample, pca_dim, seed_pca, seed_gmm, tag):
                """PCA + codebook(s) for one branch. With probe selection on
                (gmm_probe_candidates > 1) the codebook is the probe-best of
                independently-seeded candidates, each fitted on the SAME
                sample feed (select_codebook_by_probe docstring); with
                gmm_ensemble > 1 the branch gets that many independently-
                seeded sub_k-center codebooks (concatenated downstream);
                gmm_backend="sklearn" is the external-codebook control (see
                the config field). Returns (pca, [gmm, ...])."""
                pca = PCAEstimator(pca_dim).fit_batch(
                    ColumnSampler(config.num_pca_samples, seed=seed_pca)(sample)
                )
                reduced = pca(sample)

                def fit_candidate(em_seed, k_centers=sub_k, _cache={}):
                    # one sample draw per branch: the seed is fixed, so
                    # ensemble members would redo an identical multi-GB
                    # gather per member without the memo
                    if "s" not in _cache:
                        _cache["s"] = ColumnSampler(
                            config.num_gmm_samples, seed=seed_gmm
                        )(reduced)
                    gmm_sample = _cache["s"]
                    if config.gmm_backend == "sklearn":
                        return _fit_sklearn_gmm(
                            gmm_sample, k_centers, em_seed, config
                        )
                    return GaussianMixtureModelEstimator(
                        k_centers, seed=em_seed, n_init=config.gmm_n_init,
                    ).fit(gmm_sample)

                if config.gmm_probe_candidates > 1 and ens == 1:
                    from keystone_tpu.pipelines._fisher import (
                        select_codebook_by_probe,
                    )

                    gmm, scores = select_codebook_by_probe(
                        fit_candidate, reduced, sample_lbls, num_classes,
                        candidates=config.gmm_probe_candidates,
                        seed=seed_gmm,
                        probe_images=config.gmm_probe_images,
                        proj_dim=config.gmm_probe_proj_dim,
                        row_chunk=config.fv_row_chunk,
                    )
                    results[f"gmm_probe_scores_{tag}"] = scores
                    return pca, [gmm]
                # 42 = the estimator's default seed; ensemble members get
                # independent, deterministic offsets
                return pca, [fit_candidate(42 + 9973 * j) for j in range(ens)]

            pca_s, gmms_s = fit_branch(
                sample_s, config.sift_pca_dim, config.seed, config.seed + 1,
                "sift",
            )
            pca_l, gmms_l = fit_branch(
                sample_l, config.lcs_pca_dim, config.seed + 7, config.seed + 8,
                "lcs",
            )
        del sample_s, sample_l

        def l1_keys(branch_key):
            """Raw-pytree l1 names, one per ensemble member (the historical
            single-codebook name when ens == 1 — checkpoints/tests keep
            their key)."""
            if ens == 1:
                return [f"l1_{branch_key}"]
            return [f"l1_{branch_key}{j}" for j in range(ens)]

        dtype = jnp.dtype(config.desc_dtype)
        # Chunks land in preallocated buffers via donated dynamic_update_slice
        # (in-place under XLA), not a trailing jnp.concatenate — the concat
        # would transiently hold parts + result (~2× one branch of HBM).
        _upd = jax.jit(
            lambda buf, part, i0: jax.lax.dynamic_update_slice_in_dim(
                buf, part, i0, 0
            ),
            donate_argnums=(0,),
        )

        # ONE compiled program per chunk: extract (both branches) + PCA +
        # cast. Eagerly these are ~10 separate dispatches each paying a full
        # HBM round trip over the (chunk, n_desc, 128) tensors; fused, the
        # projections ride the extractor epilogues. PCA mats are ARGUMENTS
        # (not closure constants) so a warm-run refit reuses the executable.
        @jax.jit
        def _reduce_chunk(imgs, mat_s, mat_l):
            return (
                (sift_descs(imgs) @ mat_s).astype(dtype),
                (lcs_descs(imgs) @ mat_l).astype(dtype),
            )

        @jax.jit
        def _reduce_cached(sd, ld, mat_s, mat_l):
            return (
                (sd @ mat_s).astype(dtype),
                (ld @ mat_l).astype(dtype),
            )

        def reduce_split(src, use_cache: bool = False):
            """One pass over ``src``: descriptors → PCA → ``dtype`` buffers;
            returns (raw pytree for the FV block nodes, int labels).

            Chunk acquisition is double-buffered (``iter_prefetched_chunks``):
            chunk t+1's host slice / host→device transfer / on-device
            generation is dispatched ahead of need while the device
            extracts chunk t. The producer only FETCHES — desc_cache pops
            stay in the consuming loop, so the pass-A memo is read during
            run-ahead and popped at consumption without a race."""
            from keystone_tpu.core.dataset import iter_prefetched_chunks

            def fetch(i0, i1):
                # cached chunks skip the fetch entirely (None marker);
                # run-ahead must not pop — membership of FUTURE keys is
                # stable because pops happen at consumption, in order
                if use_cache and (i0, i1) in desc_cache:
                    return None
                return src.chunk(i0, i1)

            red_s = red_l = None
            lbl_parts = []
            with Timer("streaming.reduce.extract_chunks", log=False):
                for (i0, i1), fetched in iter_prefetched_chunks(
                    fetch, src.n, chunk
                ):
                    if fetched is None:
                        sd, ld, lbls = desc_cache.pop((i0, i1))
                        ps, pl = _reduce_cached(
                            sd, ld, pca_s.pca_mat, pca_l.pca_mat
                        )
                    else:
                        imgs, lbls = fetched
                        ps, pl = _reduce_chunk(
                            imgs, pca_s.pca_mat, pca_l.pca_mat
                        )
                    if red_s is None:
                        red_s = jnp.zeros((src.n, *ps.shape[1:]), dtype)
                        red_l = jnp.zeros((src.n, *pl.shape[1:]), dtype)
                    red_s = _upd(red_s, ps, i0)
                    red_l = _upd(red_l, pl, i0)
                    lbl_parts.append(lbls)
            with Timer("streaming.reduce.l1_norms", log=False):
                raw = {"sift": red_s, "lcs": red_l}
                for key, red, gmms in (
                    ("sift", red_s, gmms_s), ("lcs", red_l, gmms_l)
                ):
                    for lk, g in zip(l1_keys(key), gmms):
                        raw[lk] = fisher_l1_norms(
                            red, g, config.fv_row_chunk
                        )
            # ONE host pull for every chunk's labels (device concat first) —
            # per-chunk np.asarray would serialize a round trip per chunk
            labels_np = np.asarray(
                jnp.concatenate([jnp.asarray(l) for l in lbl_parts])
            )
            return raw, labels_np

        with Timer("streaming.reduce_train"):
            raw_train, train_labels = reduce_split(train_src, use_cache=True)
        desc_cache.clear()  # nothing may pin raw descriptors past this point

        # planner-derived solver knobs (explicit config/env values win —
        # see _resolve_solver_knobs): the resident reduced descriptors +
        # l1 tensors are the fixed HBM the block solve must fit next to
        config = _resolve_solver_knobs(
            config, train_src.n, num_classes, sub_k=sub_k,
            fixed_bytes=sum(v.nbytes for v in raw_train.values()),
        )
        # per-MEMBER block counts (the grouping unit: groups cannot span
        # ensemble members — each member is its own normalized FV)
        blocks_s = 2 * sub_k // (config.block_size // config.sift_pca_dim)
        blocks_l = 2 * sub_k // (config.block_size // config.lcs_pca_dim)

        def make_nodes(cache_s: int, cache_l: int):
            """Both branches' block nodes — ONE construction site so solver
            and eval features can only differ in cache grouping. Ensemble
            members concatenate: the feature layout is
            [sift member 0 | ... | sift member ens-1 | lcs ...]."""
            nodes = []
            for key, gmms, cache in (
                ("sift", gmms_s, cache_s), ("lcs", gmms_l, cache_l)
            ):
                for lk, g in zip(l1_keys(key), gmms):
                    nodes += make_fisher_block_nodes(
                        g, config.block_size, key=key, l1_key=lk,
                        row_chunk=config.fv_row_chunk, cache_blocks=cache,
                    )
            return nodes

        nodes = make_nodes(config.fv_cache_blocks, config.fv_cache_blocks)
        cache_dtype = jnp.dtype(config.fv_cache_dtype) if config.fv_cache_blocks else None
        labels_ind = ClassLabelIndicatorsFromIntLabels(num_classes)(
            jnp.asarray(train_labels)
        )

        with Timer("fit.block_weighted_least_squares_streaming"):
            model = BlockWeightedLeastSquaresEstimator(
                config.block_size, config.num_iter, config.lam,
                config.mixture_weight,
            ).fit_streaming(
                nodes, raw_train, labels_ind, cache_dtype=cache_dtype,
                checkpoint_path=config.solver_checkpoint or None,
                checkpoint_every=config.solver_checkpoint_every,
            )
        del raw_train

        with Timer("eval.top5_streaming"):
            with Timer("eval.reduce_test"):
                raw_test, test_labels = reduce_split(test_src)
            # Test-side nodes regroup to FULL-branch cache groups when a
            # branch's test FV fits a modest budget: one posterior pass per
            # branch instead of blocks/fv_cache_blocks passes (the solver's
            # groups are sized for the 10-20x larger train set). Each
            # branch gated on its OWN buffer size in the actual cache dtype.
            eval_nodes = nodes
            if config.fv_cache_blocks:
                item = cache_dtype.itemsize
                budget = 1 << 30  # per-branch group-buffer cap

                def eval_cache(blocks: int) -> int:
                    bytes_ = test_src.n * blocks * config.block_size * item
                    return blocks if bytes_ < budget else config.fv_cache_blocks

                eval_nodes = make_nodes(
                    eval_cache(blocks_s), eval_cache(blocks_l)
                )
            from keystone_tpu.core.cache import get_cache as _get_cache

            with Timer("eval.predict"):
                from keystone_tpu.utils import knobs as _knobs

                if (
                    _get_cache() is not None
                    and _knobs.get("KEYSTONE_EVAL_CACHED_TIMING")
                ):
                    # cached-vs-cold predict evidence (bench rows ONLY —
                    # the env flag keeps ordinary cache-enabled runs from
                    # paying a second predict): the first call computes +
                    # memoizes the whole predict, the second returns the
                    # stored scores with zero re-featurization. Explicit
                    # syncs bound each number to its own work (the async
                    # headline row never takes this branch — no cache is
                    # active there).
                    import time as _time

                    model = jax.block_until_ready(model)
                    t0 = _time.perf_counter()
                    scores = jax.block_until_ready(streaming_predict(
                        model, eval_nodes, raw_test, cache_dtype
                    ))
                    results["predict_cold_s"] = round(
                        _time.perf_counter() - t0, 3
                    )
                    t0 = _time.perf_counter()
                    scores = jax.block_until_ready(streaming_predict(
                        model, eval_nodes, raw_test, cache_dtype
                    ))
                    results["predict_cached_s"] = round(
                        _time.perf_counter() - t0, 3
                    )
                else:
                    scores = streaming_predict(
                        model, eval_nodes, raw_test, cache_dtype
                    )
            top5 = TopKClassifier(k=min(5, num_classes))(scores)
            results["test_top5_error"] = get_err_percent(top5, test_labels)
            top1 = TopKClassifier(k=1)(scores)
            results["test_top1_error"] = get_err_percent(top1, test_labels)

    results["wallclock_s"] = total.elapsed
    results["feature_dim"] = 2 * (
        config.sift_pca_dim + config.lcs_pca_dim
    ) * config.vocab_size
    logger.info(
        "streaming TEST top-5 error: %.2f%%  top-1: %.2f%%  (d=%d)",
        results["test_top5_error"],
        results["test_top1_error"],
        results["feature_dim"],
    )
    return results


def _run_streaming_ingest(config: ImageNetSiftLcsFVConfig) -> dict:
    """Never-resident flagship fit over real tar archives: the streaming
    ingest pipeline (``core/ingest.py``) decodes into a bounded ring of
    recycled host buffers and extraction consumes batches AS THEY ARRIVE —
    the raw image tensor never exists on host or device, so the dataset
    may exceed host RAM.

    Two passes per split, mirroring ``_run_streaming``'s structure: pass A
    streams a prefix of the archives for the PCA/GMM descriptor sample;
    pass B re-streams everything, reducing each decoded batch to the
    resident bf16 descriptors through ONE fixed-shape jitted program
    (zero steady-state recompiles — ``ingest_reduce_compiles`` records the
    jit cache size as evidence). The solver tail is the out-of-core
    weighted BCD of the plain streaming path."""
    import jax

    from keystone_tpu.core.ingest import ingest_buffers
    from keystone_tpu.learning.block_linear import streaming_predict
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.learning.pca import PCAEstimator
    from keystone_tpu.loaders.imagenet import stream_imagenet_batches
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )
    from keystone_tpu.ops.stats import BatchSignedHellingerMapper, ColumnSampler
    from keystone_tpu.telemetry import get_registry

    results: dict = {}
    reg = get_registry()
    bs = config.ingest_batch
    hw = (config.image_hw, config.image_hw)
    num_classes = IMAGENET_NUM_CLASSES
    sift = SIFTExtractor()
    hellinger = BatchSignedHellingerMapper()
    lcs = LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch)
    dtype = jnp.dtype(config.desc_dtype)

    def sift_descs(imgs):
        return hellinger(sift(GrayScaler()(imgs)[..., 0]))

    # ONE compiled program per decoded batch (both branches + PCA + cast),
    # always at the FULL fixed (ingest_batch, H, W, 3) shape the ring
    # yields — the steady-state fit performs zero recompiles after the
    # first batch. PCA mats are arguments so train and test passes share
    # the executable.
    @jax.jit
    def _reduce_batch(imgs, mat_s, mat_l):
        return (
            (sift_descs(imgs) @ mat_s).astype(dtype),
            (lcs(imgs) @ mat_l).astype(dtype),
        )

    @jax.jit
    def _batch_descs(imgs):
        return sift_descs(imgs), lcs(imgs)

    def keep_rows(parts, labels):
        """Slice a reduced pair down to the labeled rows. Full all-labeled
        batches (the steady state) pass through untouched; ragged batches
        (final partial / unlabeled entries) pay one device gather."""
        keep = np.nonzero(labels >= 0)[0]
        if keep.size == labels.shape[0]:
            return parts, keep.size
        idx = jnp.asarray(keep, jnp.int32)
        return tuple(p[idx] for p in parts), keep.size

    decode_s0 = reg.get_counter("ingest.decode_s")
    stall_s0 = reg.get_counter("ingest.stall_s")
    with use_mesh(get_mesh()), Timer("ImageNetSiftLcsFV.streaming_ingest") as total:
        # Pass A: descriptor sample for the PCA/GMM fits from the stream's
        # first ~sample_images labeled rows; the early break abandons the
        # feed, whose cleanup stops the decode workers.
        s_parts, l_parts, seen = [], [], 0
        for imgs, labels in stream_imagenet_batches(
            config.train_location, config.train_labels, hw, bs
        ):
            (sd, ld), n = keep_rows(_batch_descs(imgs), labels)
            if n == 0:
                continue
            s_parts.append(sd[:n])
            l_parts.append(ld[:n])
            seen += n
            if seen >= config.sample_images:
                break
        if not s_parts:
            raise ValueError(
                f"no labeled images streamed from {config.train_location}"
            )
        sample_s = jnp.concatenate(s_parts) if len(s_parts) > 1 else s_parts[0]
        sample_l = jnp.concatenate(l_parts) if len(l_parts) > 1 else l_parts[0]
        del s_parts, l_parts

        with Timer("streaming.fit_pca_gmm"):
            pca_s = PCAEstimator(config.sift_pca_dim).fit_batch(
                ColumnSampler(config.num_pca_samples, seed=config.seed)(sample_s)
            )
            gmm_s = GaussianMixtureModelEstimator(
                config.vocab_size, n_init=config.gmm_n_init
            ).fit(ColumnSampler(
                config.num_gmm_samples, seed=config.seed + 1
            )(pca_s(sample_s)))
            pca_l = PCAEstimator(config.lcs_pca_dim).fit_batch(
                ColumnSampler(
                    config.num_pca_samples, seed=config.seed + 7
                )(sample_l)
            )
            gmm_l = GaussianMixtureModelEstimator(
                config.vocab_size, n_init=config.gmm_n_init
            ).fit(ColumnSampler(
                config.num_gmm_samples, seed=config.seed + 8
            )(pca_l(sample_l)))
        del sample_s, sample_l

        def reduce_stream(location, labels_path):
            """One full streaming pass: decoded batches → reduced bf16
            descriptors + l1 norms (the resident representation). Raw
            images live only inside the ingest ring."""
            ps_parts, pl_parts, lbl_parts = [], [], []
            for imgs, labels in stream_imagenet_batches(
                location, labels_path, hw, bs
            ):
                pair = _reduce_batch(imgs, pca_s.pca_mat, pca_l.pca_mat)
                (ps, pl), n = keep_rows(pair, labels)
                if n == 0:
                    continue
                ps_parts.append(ps[:n])
                pl_parts.append(pl[:n])
                lbl_parts.append(labels[labels >= 0])
            if not ps_parts:
                raise ValueError(f"no labeled images streamed from {location}")
            red_s = (jnp.concatenate(ps_parts)
                     if len(ps_parts) > 1 else ps_parts[0])
            red_l = (jnp.concatenate(pl_parts)
                     if len(pl_parts) > 1 else pl_parts[0])
            raw = {
                "sift": red_s,
                "l1_sift": fisher_l1_norms(red_s, gmm_s, config.fv_row_chunk),
                "lcs": red_l,
                "l1_lcs": fisher_l1_norms(red_l, gmm_l, config.fv_row_chunk),
            }
            return raw, np.concatenate(lbl_parts)

        with Timer("streaming.reduce_train"):
            raw_train, train_labels = reduce_stream(
                config.train_location, config.train_labels
            )
        n_train = int(train_labels.shape[0])

        config = _resolve_solver_knobs(
            config, n_train, num_classes, sub_k=config.vocab_size,
            fixed_bytes=sum(v.nbytes for v in raw_train.values()),
        )
        blocks_s = 2 * config.vocab_size // (
            config.block_size // config.sift_pca_dim
        )
        blocks_l = 2 * config.vocab_size // (
            config.block_size // config.lcs_pca_dim
        )

        def make_nodes(cache_s: int, cache_l: int):
            return make_fisher_block_nodes(
                gmm_s, config.block_size, key="sift", l1_key="l1_sift",
                row_chunk=config.fv_row_chunk, cache_blocks=cache_s,
            ) + make_fisher_block_nodes(
                gmm_l, config.block_size, key="lcs", l1_key="l1_lcs",
                row_chunk=config.fv_row_chunk, cache_blocks=cache_l,
            )

        nodes = make_nodes(config.fv_cache_blocks, config.fv_cache_blocks)
        cache_dtype = (
            jnp.dtype(config.fv_cache_dtype) if config.fv_cache_blocks else None
        )
        labels_ind = ClassLabelIndicatorsFromIntLabels(num_classes)(
            jnp.asarray(train_labels)
        )
        with Timer("fit.block_weighted_least_squares_streaming"):
            model = BlockWeightedLeastSquaresEstimator(
                config.block_size, config.num_iter, config.lam,
                config.mixture_weight,
            ).fit_streaming(
                nodes, raw_train, labels_ind, cache_dtype=cache_dtype,
                checkpoint_path=config.solver_checkpoint or None,
                checkpoint_every=config.solver_checkpoint_every,
            )
        del raw_train

        with Timer("eval.top5_streaming"):
            # test archives stream only now — nothing test-side was
            # resident through the memory-critical solve
            raw_test, test_labels = reduce_stream(
                config.test_location, config.test_labels
            )
            eval_nodes = nodes
            if config.fv_cache_blocks:
                n_test = int(test_labels.shape[0])
                item = cache_dtype.itemsize
                budget = 1 << 30  # per-branch group-buffer cap

                def eval_cache(blocks: int) -> int:
                    bytes_ = n_test * blocks * config.block_size * item
                    return blocks if bytes_ < budget else config.fv_cache_blocks

                eval_nodes = make_nodes(
                    eval_cache(blocks_s), eval_cache(blocks_l)
                )
            scores = streaming_predict(model, eval_nodes, raw_test, cache_dtype)
            top5 = TopKClassifier(k=min(5, num_classes))(scores)
            results["test_top5_error"] = get_err_percent(top5, test_labels)
            top1 = TopKClassifier(k=1)(scores)
            results["test_top1_error"] = get_err_percent(top1, test_labels)

    frame_bytes = hw[0] * hw[1] * 3 * 4
    n_total = n_train + int(test_labels.shape[0])
    results["wallclock_s"] = total.elapsed
    results["feature_dim"] = 2 * (
        config.sift_pca_dim + config.lcs_pca_dim
    ) * config.vocab_size
    # never-resident evidence pair: the raw decoded footprint the in-core
    # path would have materialized vs the bounded working set this path
    # actually held (the ingest ring), plus decode/stall attribution and
    # the zero-recompile pin
    results["ingest_images"] = n_total
    results["ingest_raw_bytes"] = int(n_total * frame_bytes)
    results["ingest_peak_host_bytes"] = int(
        ingest_buffers() * bs * frame_bytes
    )
    results["ingest_decode_s"] = round(
        reg.get_counter("ingest.decode_s") - decode_s0, 3
    )
    results["ingest_stall_s"] = round(
        reg.get_counter("ingest.stall_s") - stall_s0, 3
    )
    results["ingest_reduce_compiles"] = int(_reduce_batch._cache_size())
    logger.info(
        "streaming-ingest TEST top-5: %.2f%%  top-1: %.2f%%  (raw %.1f MB "
        "streamed through a %.1f MB ring)",
        results["test_top5_error"], results["test_top1_error"],
        results["ingest_raw_bytes"] / 1e6,
        results["ingest_peak_host_bytes"] / 1e6,
    )
    return results


def fit_streaming_ingest(config: ImageNetSiftLcsFVConfig) -> dict:
    """Public entry for the never-resident streaming-ingest fit (the
    ``--ingest`` path of :func:`run`); validates then streams."""
    config.validate()
    if not config.ingest:
        config = dataclasses.replace(config, ingest=True, streaming=True)
        config.validate()
    return _run_streaming_ingest(config)


def flagship_config(**overrides) -> ImageNetSiftLcsFVConfig:
    """The measured reference-dim streaming configuration (BASELINE.md
    flagship row; `ImageNetSiftLcsFV.scala:197-218` dims): vocab 256,
    PCA-64, 2 branches → d=65 536, 1000 classes, out-of-core weighted BCD.
    Used by ``scripts/flagship_imagenet.py`` and ``BENCH_FLAGSHIP=1``."""
    cfg = dict(
        sift_pca_dim=64,
        lcs_pca_dim=64,
        vocab_size=256,
        num_pca_samples=2000000,
        num_gmm_samples=2000000,
        lam=6e-5,
        mixture_weight=0.25,
        # block_size / fv_cache_blocks stay on auto: with the optimizer
        # off they resolve to the measured hand values (4096 / 2-block
        # groups, the BASELINE.md configuration); with KEYSTONE_OPTIMIZER
        # on they come from the HBM-budget plan (_resolve_solver_knobs)
        synthetic_train=102400,
        synthetic_test=5120,
        synthetic_classes=1000,
        synthetic_hw=64,
        # noise 0.6 is the non-vacuous quality regime (measured top-5 4.67%
        # vs 99.5% chance; the generator default 0.08 yields separable
        # prototypes and 0% error — a plumbing check, not evidence).
        # Shuffled-label control protocol: same config with
        # shuffle_labels=True must collapse to ~chance (BASELINE.md).
        synthetic_noise=0.6,
        streaming=True,
        extract_chunk=2048,
        sample_images=8192,
        fv_row_chunk=1024,
    )
    cfg.update(overrides)
    return ImageNetSiftLcsFVConfig(**cfg)


def small_config(**overrides) -> ImageNetSiftLcsFVConfig:
    """The BASELINE.md small-config row (2048/512 imgs at the default 96²,
    16 classes, vocab 16) — ONE definition shared by ``bench.py`` and
    ``scripts/cpu_baseline.py`` so the TPU/CPU sides of
    ``imagenet_small_vs_cpu_baseline`` can never drift apart."""
    cfg = dict(
        synthetic_train=2048, synthetic_test=512, synthetic_classes=16,
        vocab_size=16, sift_pca_dim=64, lcs_pca_dim=64,
        num_pca_samples=1000000, num_gmm_samples=1000000,
    )
    cfg.update(overrides)
    return ImageNetSiftLcsFVConfig(**cfg)


def check_graph():
    """Pipeline contracts for `keystone-tpu check`: the two-branch
    descriptor-reduction DAG (gray → SIFT → Hellinger → PCA zipped with
    LCS → PCA over the SAME input images — the streaming path's per-chunk
    compiled unit), plus the weighted-solver fit/apply pair.  PCA mats are
    zero placeholders: the checker reads shapes, never weights."""
    import jax

    from jax.sharding import PartitionSpec as P

    from keystone_tpu.analysis.check import FitApply, PipelineContract
    from keystone_tpu.core.pipeline import ConcatFeatures, Transformer, dag
    from keystone_tpu.learning.pca import BatchPCATransformer
    from keystone_tpu.ops.stats import BatchSignedHellingerMapper

    config = small_config()
    hw = 64  # contract dims: the layout, not the flagship scale
    sift = SIFTExtractor()
    lcs = LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch)
    squeeze = Transformer.from_fn(lambda im: im[..., 0], name="squeeze_gray")
    spec = jax.ShapeDtypeStruct((1, hw, hw, 3), jnp.float32)
    d_sift = jax.eval_shape(
        lambda im: sift.apply_batch(squeeze.apply_batch(
            GrayScaler().apply_batch(im))), spec
    ).shape[-1]
    d_lcs = jax.eval_shape(lcs.apply_batch, spec).shape[-1]
    pipe = dag(
        [
            GrayScaler(), squeeze, sift, BatchSignedHellingerMapper(),
            BatchPCATransformer(
                pca_mat=jnp.zeros((d_sift, config.sift_pca_dim), jnp.float32)
            ),
            lcs,
            BatchPCATransformer(
                pca_mat=jnp.zeros((d_lcs, config.lcs_pca_dim), jnp.float32)
            ),
            ConcatFeatures(axis=1),
        ],
        [(-1,), (0,), (1,), (2,), (3,), (-1,), (5,), (4, 6)],
    )
    sample = jax.ShapeDtypeStruct((2, hw, hw, 3), jnp.float32)
    # the fit/apply pair is the DAG's own reduced-descriptor interface
    # (what the FV encode + weighted solver consume), derived by two
    # INDEPENDENT traces at train-chunk vs test-chunk batch sizes — the
    # production streaming fit and eval paths share these branch nodes,
    # so C3 here guards batch-dependent shape logic
    return [PipelineContract(
        name="imagenet.descriptor_dag",
        pipe=pipe,
        sample=sample,
        spec=P("data", None, None, None),
        fit_apply=[FitApply(
            "weighted_block_solver",
            fit_aval=jax.eval_shape(pipe.apply_batch, sample),
            apply_aval=jax.eval_shape(
                pipe.apply_batch,
                jax.ShapeDtypeStruct((1, hw, hw, 3), jnp.float32),
            ),
        )],
    )]


def _run_bucketed(config: ImageNetSiftLcsFVConfig) -> dict:
    """Variable-size ingest: both branches (SIFT on gray, LCS on RGB) over
    size-bucketed image groups — per-bucket static shapes, no global resize
    (``_fisher.fit_fisher_branch_buckets``; match
    ``loaders/ImageLoaderUtils.scala:47-93``)."""
    from keystone_tpu.loaders.imagenet import load_imagenet_bucketed
    from keystone_tpu.pipelines._fisher import (
        apply_featurizer_buckets,
        fit_fisher_branch_buckets,
    )
    from keystone_tpu.pipelines.voc_sift_fisher import parse_buckets

    buckets = parse_buckets(config.buckets)
    train = load_imagenet_bucketed(
        config.train_location, config.train_labels, buckets
    )
    test = load_imagenet_bucketed(config.test_location, config.test_labels, buckets)
    num_classes = IMAGENET_NUM_CLASSES

    results: dict = {}
    with use_mesh(get_mesh()), Timer("ImageNetSiftLcsFV.pipeline") as total:
        rgb_train = [(hw, jnp.asarray(imgs)) for hw, imgs, _ in train]
        gray_train = [(hw, GrayScaler()(x)[..., 0]) for hw, x in rgb_train]

        sift_featurizer, sift_train, sift_counts = fit_fisher_branch_buckets(
            SIFTExtractor(),
            gray_train,
            config.sift_pca_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            hellinger_first=True,
            gmm_n_init=config.gmm_n_init,
        )
        lcs_featurizer, lcs_train, lcs_counts = fit_fisher_branch_buckets(
            LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch),
            rgb_train,
            config.lcs_pca_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed + 7,
            gmm_n_init=config.gmm_n_init,
        )

        train_feats = jnp.concatenate([sift_train, lcs_train], axis=1)
        train_labels = np.concatenate([lb for _, _, lb in train])
        labels = ClassLabelIndicatorsFromIntLabels(num_classes)(
            jnp.asarray(train_labels)
        )

        config = _resolve_solver_knobs(
            config, int(train_feats.shape[0]), num_classes,
            fixed_bytes=train_feats.nbytes,
        )
        with Timer("fit.block_weighted_least_squares"):
            model = BlockWeightedLeastSquaresEstimator(
                config.block_size, config.num_iter, config.lam, config.mixture_weight
            ).fit(train_feats, labels)

        with Timer("eval.top5"):
            rgb_test = [(hw, jnp.asarray(imgs)) for hw, imgs, _ in test]
            gray_test = [(hw, GrayScaler()(x)[..., 0]) for hw, x in rgb_test]
            test_feats = jnp.concatenate(
                [
                    apply_featurizer_buckets(sift_featurizer, gray_test),
                    apply_featurizer_buckets(lcs_featurizer, rgb_test),
                ],
                axis=1,
            )
            scores = model(test_feats)
            test_labels = np.concatenate([lb for _, _, lb in test])
            top5 = TopKClassifier(k=min(5, num_classes))(scores)
            results["test_top5_error"] = get_err_percent(top5, test_labels)
            top1 = TopKClassifier(k=1)(scores)
            results["test_top1_error"] = get_err_percent(top1, test_labels)

    results["buckets"] = {
        f"{hw[0]}x{hw[1]}": {
            "images": int(imgs.shape[0]),
            "sift_descriptors": sc,
            "lcs_descriptors": lc,
        }
        for (hw, imgs, _), sc, lc in zip(train, sift_counts, lcs_counts)
    }
    results["wallclock_s"] = total.elapsed
    logger.info(
        "TEST top-5 error: %.2f%%  top-1: %.2f%%  buckets: %s",
        results["test_top5_error"], results["test_top1_error"],
        results["buckets"],
    )
    return results


def run(config: ImageNetSiftLcsFVConfig) -> dict:
    # unconditional: gmm_backend/gmm_ensemble misconfigurations must fail
    # loudly on EVERY path — the in-core and plain-streaming paths used to
    # silently ignore them (ADVICE.md round 5)
    config.validate()
    if config.ingest:
        return _run_streaming_ingest(config)
    if config.buckets:
        if config.streaming:
            return _run_streaming_bucketed(config)
        return _run_bucketed(config)
    if config.streaming:
        if config.train_location:
            hw = (config.image_hw, config.image_hw)
            train = load_imagenet(config.train_location, config.train_labels, hw)
            test = load_imagenet(config.test_location, config.test_labels, hw)
            return _run_streaming(
                config, _ArraySource(*train), _ArraySource(*test),
                IMAGENET_NUM_CLASSES,
            )
        hw = (config.synthetic_hw, config.synthetic_hw)
        return _run_streaming(
            config,
            _SyntheticSource(config.synthetic_train, config.synthetic_classes,
                             hw, seed=1, noise=config.synthetic_noise,
                             shuffle_labels=config.shuffle_labels),
            _SyntheticSource(config.synthetic_test, config.synthetic_classes,
                             hw, seed=2, noise=config.synthetic_noise),
            config.synthetic_classes,
        )
    if config.train_location:
        hw = (config.image_hw, config.image_hw)
        train = load_imagenet(config.train_location, config.train_labels, hw)
        test = load_imagenet(config.test_location, config.test_labels, hw)
        num_classes = IMAGENET_NUM_CLASSES
    else:
        hw = (config.synthetic_hw, config.synthetic_hw)
        train = synthetic_imagenet_device(
            config.synthetic_train, config.synthetic_classes, hw, seed=1,
            noise=config.synthetic_noise,
        )
        if config.shuffle_labels:
            rng = np.random.default_rng(7)
            train = (train[0], rng.integers(
                0, config.synthetic_classes, size=config.synthetic_train
            ).astype(np.int32))
        test = synthetic_imagenet_device(
            config.synthetic_test, config.synthetic_classes, hw, seed=2,
            noise=config.synthetic_noise,
        )
        num_classes = config.synthetic_classes

    results: dict = {}
    with use_mesh(get_mesh()), Timer("ImageNetSiftLcsFV.pipeline") as total:
        train_imgs = jnp.asarray(train[0])
        test_imgs = jnp.asarray(test[0])
        gray_train = GrayScaler()(train_imgs)[..., 0]
        gray_test = GrayScaler()(test_imgs)[..., 0]

        # SIFT branch: Hellinger on raw descriptors before PCA (:52-53)
        sift_featurizer, sift_train = fit_fisher_branch(
            SIFTExtractor(),
            gray_train,
            config.sift_pca_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            hellinger_first=True,
            gmm_n_init=config.gmm_n_init,
        )
        # LCS branch on RGB (:96-148)
        lcs_featurizer, lcs_train = fit_fisher_branch(
            LCSExtractor(config.lcs_stride, config.lcs_border, config.lcs_patch),
            train_imgs,
            config.lcs_pca_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed + 7,
            gmm_n_init=config.gmm_n_init,
        )

        # ZipVectors over the two branches (:179-180)
        train_feats = jnp.concatenate([sift_train, lcs_train], axis=1)
        labels = ClassLabelIndicatorsFromIntLabels(num_classes)(jnp.asarray(train[1]))

        config = _resolve_solver_knobs(
            config, int(train_feats.shape[0]), num_classes,
            fixed_bytes=train_feats.nbytes,
        )
        with Timer("fit.block_weighted_least_squares"):
            model = BlockWeightedLeastSquaresEstimator(
                config.block_size, config.num_iter, config.lam, config.mixture_weight
            ).fit(train_feats, labels)

        with Timer("eval.top5"):
            test_feats = jnp.concatenate(
                [sift_featurizer(gray_test), lcs_featurizer(test_imgs)], axis=1
            )
            scores = model(test_feats)
            top5 = TopKClassifier(k=min(5, num_classes))(scores)
            results["test_top5_error"] = get_err_percent(top5, test[1])
            top1 = TopKClassifier(k=1)(scores)
            results["test_top1_error"] = get_err_percent(top1, test[1])

    results["wallclock_s"] = total.elapsed
    logger.info(
        "TEST top-5 error: %.2f%%  top-1: %.2f%%",
        results["test_top5_error"],
        results["test_top1_error"],
    )
    return results


def main(argv=None):
    print(
        json.dumps(
            run(parse_config(ImageNetSiftLcsFVConfig, argv, prog="ImageNetSiftLcsFV"))
        )
    )


if __name__ == "__main__":
    main()
