"""Shared pipeline scaffolding: the load→distribute→labels→evaluate skeleton
every app repeats (the analog of the reference's per-app boilerplate,
SURVEY.md §2.11)."""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.core.dataset import Dataset
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels, MaxClassifier
from keystone_tpu.parallel import distribute


def prepare_labeled(x, y, num_classes: int):
    """Distribute (pad+shard) data and labels; returns
    (data Dataset, sharded int labels, ±1 indicator matrix)."""
    ds = distribute(jnp.asarray(x))
    y_sharded = distribute(jnp.asarray(y)).data
    indicators = ClassLabelIndicatorsFromIntLabels(num_classes)(y_sharded)
    return ds, y_sharded, indicators


def error_percent(scores, actuals, mask, num_classes: int):
    """argmax → masked multiclass error, in percent, as a DEVICE scalar.

    Kept on device so pipelines can batch every stage's metric into one
    device→host transfer at the end (each transfer is a full round-trip on a
    tunneled runtime); callers ``float()`` / ``np.asarray`` the result(s) once.
    """
    preds = MaxClassifier()(scores)
    return 100.0 * MulticlassClassifierEvaluator(num_classes).error(
        preds, actuals, mask
    )
