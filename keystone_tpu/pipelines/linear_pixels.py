"""LinearPixels: CIFAR grayscale → vectorize → OLS.

Reference: ``pipelines/images/cifar/LinearPixels.scala:14-78``.
"""

from __future__ import annotations

import dataclasses
import json

import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.config import parse_config
from keystone_tpu.core.pipeline import chain
from keystone_tpu.learning import LinearMapEstimator
from keystone_tpu.loaders.cifar import CIFAR_NUM_CLASSES, load_cifar_binary, synthetic_cifar_device
from keystone_tpu.ops.images import GrayScaler, ImageVectorizer
from keystone_tpu.pipelines._common import error_percent, prepare_labeled
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger

logger = get_logger("keystone_tpu.pipelines.linear_pixels")


@dataclasses.dataclass
class LinearPixelsConfig:
    train_location: str = ""
    test_location: str = ""
    synthetic_train: int = 10000
    synthetic_test: int = 2000


def run(config: LinearPixelsConfig) -> dict:
    if config.train_location:
        train = load_cifar_binary(config.train_location)
        test = load_cifar_binary(config.test_location)
    else:
        train = synthetic_cifar_device(config.synthetic_train, seed=1)
        test = synthetic_cifar_device(config.synthetic_test, seed=2)

    results: dict = {}
    with use_mesh(get_mesh()), Timer("LinearPixels.pipeline") as total:
        featurizer = chain(GrayScaler(), ImageVectorizer())
        train_ds, train_y, indicators = prepare_labeled(*train, CIFAR_NUM_CLASSES)
        feats = featurizer(train_ds)
        model = LinearMapEstimator().fit(feats.data, indicators, mask=feats.mask)
        predict = featurizer >> model

        train_err = error_percent(
            predict(train_ds).data, train_y, train_ds.mask, CIFAR_NUM_CLASSES
        )
        test_ds, test_y, _ = prepare_labeled(*test, CIFAR_NUM_CLASSES)
        test_err = error_percent(
            predict(test_ds).data, test_y, test_ds.mask, CIFAR_NUM_CLASSES
        )
        # single host sync of the whole pipeline
        errs = np.asarray(jnp.stack([train_err, test_err]))
    results["train_error"], results["test_error"] = float(errs[0]), float(errs[1])
    results["wallclock_s"] = total.elapsed
    logger.info("Training error: %.2f%%  Test error: %.2f%%", results["train_error"], results["test_error"])
    return results


def main(argv=None):
    print(json.dumps(run(parse_config(LinearPixelsConfig, argv, prog="LinearPixels"))))


if __name__ == "__main__":
    main()
