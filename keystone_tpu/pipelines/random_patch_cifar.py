"""RandomPatchCifar: whitened random-patch filters → conv → rectify → pool →
block least squares.

Reference: ``pipelines/images/cifar/RandomPatchCifar.scala:16-127``.
"""

from __future__ import annotations

import dataclasses
import json

from keystone_tpu.core.config import parse_config
from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.loaders.cifar import load_cifar_binary, synthetic_cifar_device
from keystone_tpu.pipelines._cifar_conv import (
    conv_featurizer,
    fit_and_eval,
    learn_patch_filters,
)
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger

logger = get_logger("keystone_tpu.pipelines.random_patch_cifar")


@dataclasses.dataclass
class RandomPatchCifarConfig:
    train_location: str = ""
    test_location: str = ""
    num_filters: int = 100
    patch_size: int = 6
    patch_steps: int = 1
    pool_size: int = 14
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float = 10.0
    # 0 = auto (core/plan.py precedence: explicit value > KEYSTONE_BLOCK_
    # SIZE env > HBM-budget-planned under KEYSTONE_OPTIMIZER > 4096)
    block_size: int = 0
    whitener_size: int = 100000
    seed: int = 0
    synthetic_train: int = 10000
    synthetic_test: int = 2000


def check_graph():
    """Pipeline contracts for `keystone-tpu check`: the conv featurizer
    (Convolver → SymmetricRectifier → Pooler → ImageVectorizer) over the
    CIFAR image layout — filter weights are zero placeholders, the checker
    reads shapes only — plus the solver fit/apply pair."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.analysis.check import FitApply, PipelineContract

    config = RandomPatchCifarConfig(num_filters=8)
    filters = jnp.zeros(
        (config.num_filters, config.patch_size * config.patch_size * 3),
        jnp.float32,
    )
    featurizer = conv_featurizer(
        filters, None, config.alpha, config.pool_stride, config.pool_size
    )
    sample = jax.ShapeDtypeStruct((4, 32, 32, 3), jnp.float32)
    # independent traces of the featurizer at fit vs eval batch sizes
    # (the production predict path reuses the same chain; C3 guards
    # batch-dependent shape logic)
    return [PipelineContract(
        name="cifar.conv_featurizer",
        pipe=featurizer,
        sample=sample,
        spec=P("data", None, None, None),
        fit_apply=[FitApply(
            "block_least_squares",
            fit_aval=jax.eval_shape(featurizer.apply_batch, sample),
            apply_aval=jax.eval_shape(
                featurizer.apply_batch,
                jax.ShapeDtypeStruct((2, 32, 32, 3), jnp.float32),
            ),
        )],
    )]


def run(config: RandomPatchCifarConfig) -> dict:
    if config.train_location:
        train = load_cifar_binary(config.train_location)
        test = load_cifar_binary(config.test_location)
    else:
        train = synthetic_cifar_device(config.synthetic_train, seed=1)
        test = synthetic_cifar_device(config.synthetic_test, seed=2)

    with use_mesh(get_mesh()), Timer("RandomPatchCifar.pipeline") as total:
        with Timer("learn_patch_filters.dispatch"):
            filters, whitener = learn_patch_filters(
                train[0],
                config.patch_size,
                config.patch_steps,
                config.num_filters,
                config.whitener_size,
                config.seed,
            )
        featurizer = conv_featurizer(
            filters, whitener, config.alpha, config.pool_stride, config.pool_size
        )
        # planner-derived block size (core/plan.py precedence; explicit
        # config/env values win, optimizer-off keeps the hand-tuned 4096)
        from keystone_tpu.core import plan

        block_size = plan.resolve_block_size(
            "cifar.block_solver", explicit=config.block_size or None,
            n_rows=int(train[1].shape[0]), num_classes=10, default=4096,
            quantum=128,
        )
        est = BlockLeastSquaresEstimator(block_size, 1, config.lam)
        # conv + doubled-rectifier intermediates per row, f32
        conv_hw = (32 - config.patch_size + 1) ** 2
        per_row = 3 * config.num_filters * conv_hw * 4
        results = fit_and_eval(
            featurizer,
            lambda a, b, m: est.fit(a, b, mask=m),
            train,
            test,
            per_row_intermediate_bytes=per_row,
        )
    results["wallclock_s"] = total.elapsed
    logger.info(
        "Training error: %.2f%%  Test error: %.2f%%",
        results["train_error"],
        results["test_error"],
    )
    return results


def main(argv=None):
    print(
        json.dumps(run(parse_config(RandomPatchCifarConfig, argv, prog="RandomPatchCifar")))
    )


if __name__ == "__main__":
    main()
