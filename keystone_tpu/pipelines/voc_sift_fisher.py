"""VOCSIFTFisher: SIFT → PCA → GMM → FisherVector → block least squares →
mean average precision.

Reference: ``pipelines/images/voc/VOCSIFTFisher.scala:18-158`` (defaults:
blockSize 4096, descDim 80, vocabSize 256, 1e6 samples, ``:109-123``).
"""

from __future__ import annotations

import dataclasses
import json

import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.config import parse_config
from keystone_tpu.core.pipeline import chain
from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.loaders.voc import VOC_NUM_CLASSES, load_voc, synthetic_voc_device
from keystone_tpu.ops.images import GrayScaler, SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntArrayLabels
from keystone_tpu.pipelines._fisher import fit_fisher_branch
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger

logger = get_logger("keystone_tpu.pipelines.voc_sift_fisher")


@dataclasses.dataclass
class VOCSIFTFisherConfig:
    train_location: str = ""
    train_labels: str = ""
    test_location: str = ""
    test_labels: str = ""
    desc_dim: int = 80
    vocab_size: int = 256
    num_pca_samples: int = 1000000
    num_gmm_samples: int = 1000000
    lam: float = 0.5
    # Solver column block size. 0 = auto (core/plan.py precedence:
    # explicitly-set value > KEYSTONE_BLOCK_SIZE env > HBM-budget-planned
    # under KEYSTONE_OPTIMIZER > the hand-tuned 4096).
    block_size: int = 0
    sift_scales: int = 4
    image_hw: int = 256
    # size-bucketed variable-shape ingest: comma-separated HxW ladder (e.g.
    # "128x128,192x256,256x256"). Images land in the smallest containing
    # bucket (pad, no resize) and every extractor stage compiles once per
    # bucket shape — the reference's native-size processing
    # (loaders/ImageLoaderUtils.scala:47-93) under XLA static shapes. Empty
    # -> single-frame ingest at image_hw. Real-archive paths only.
    buckets: str = ""
    pca_file: str = ""
    gmm_mean_file: str = ""
    gmm_var_file: str = ""
    gmm_wts_file: str = ""
    seed: int = 42
    # synthetic fallback (zero-egress environments)
    synthetic_train: int = 256
    synthetic_test: int = 128
    synthetic_classes: int = 8
    synthetic_hw: int = 96
    # row-chunk the extractor/FV stages (ChunkedMap) — needed at reference
    # scale (5k imgs × vocab 256) to bound per-image intermediates
    row_chunks: int = 1
    # independent GMM-EM restarts; best likelihood wins (density-fit tool —
    # see BASELINE.md on why it does not stabilize classifier quality)
    gmm_n_init: int = 1

    def validate(self):
        if self.buckets and not self.train_location:
            raise ValueError(
                "--buckets is variable-size ingest for real archives; the "
                "synthetic generator emits one size (drop --buckets or set "
                "--train-location)"
            )


def _resolved_block_size(config: VOCSIFTFisherConfig, n_rows: int,
                         num_classes: int) -> int:
    """Planner-derived solver block size (core/plan.py::resolve_block_size
    precedence; with ``KEYSTONE_OPTIMIZER=0`` this is exactly the prior
    hand-tuned 4096 unless the config/env set one explicitly)."""
    from keystone_tpu.core import plan

    return plan.resolve_block_size(
        "voc.block_solver", explicit=config.block_size or None,
        n_rows=n_rows, num_classes=num_classes, default=4096,
        quantum=max(128, config.desc_dim),
        ceiling=2 * config.desc_dim * config.vocab_size,
    )


def small_config(**overrides) -> VOCSIFTFisherConfig:
    """The BASELINE.md small-config row (1024/256 imgs 96², vocab 16) —
    ONE definition shared by ``bench.py`` and ``scripts/cpu_baseline.py``
    so the TPU/CPU sides of ``voc_small_vs_cpu_baseline`` can never drift
    apart."""
    cfg = dict(
        synthetic_train=1024, synthetic_test=256, vocab_size=16,
        num_pca_samples=1000000, num_gmm_samples=1000000,
    )
    cfg.update(overrides)
    return VOCSIFTFisherConfig(**cfg)


def check_graph():
    """Pipeline contracts for `keystone-tpu check`: the full VOC branch —
    gray → squeeze → SIFT → PCA → FV encode → normalize — at contract
    dims (PCA/GMM weights are zero placeholders; only shapes propagate),
    plus the block-solver fit/apply pair."""
    import jax

    from jax.sharding import PartitionSpec as P

    from keystone_tpu.analysis.check import FitApply, PipelineContract
    from keystone_tpu.core.pipeline import Transformer, chain as _chain
    from keystone_tpu.learning.gmm import GaussianMixtureModel
    from keystone_tpu.learning.pca import BatchPCATransformer
    from keystone_tpu.pipelines._fisher import fisher_featurizer

    desc_dim, vocab = 16, 4
    gmm = GaussianMixtureModel(
        means=jnp.zeros((vocab, desc_dim), jnp.float32),
        variances=jnp.ones((vocab, desc_dim), jnp.float32),
        weights=jnp.ones((vocab,), jnp.float32) / vocab,
    )
    squeeze = Transformer.from_fn(lambda im: im[..., 0], name="squeeze_gray")
    pipe = _chain(
        GrayScaler(), squeeze, SIFTExtractor(scales=2),
        BatchPCATransformer(pca_mat=jnp.zeros((128, desc_dim), jnp.float32)),
        fisher_featurizer(gmm),
    )
    sample = jax.ShapeDtypeStruct((2, 64, 64, 3), jnp.float32)
    # independent traces of the fitted featurizer at train vs test batch
    # sizes (the eval path calls the SAME featurizer chain; C3 guards
    # batch-dependent shape logic)
    return [PipelineContract(
        name="voc.fisher_branch",
        pipe=pipe,
        sample=sample,
        spec=P("data", None, None, None),
        fit_apply=[FitApply(
            "block_least_squares",
            fit_aval=jax.eval_shape(pipe.apply_batch, sample),
            apply_aval=jax.eval_shape(
                pipe.apply_batch,
                jax.ShapeDtypeStruct((1, 64, 64, 3), jnp.float32),
            ),
        )],
    )]


def parse_buckets(s: str):
    """``"128x128,192x256"`` -> ``[(128, 128), (192, 256)]``."""
    out = []
    for part in s.split(","):
        part = part.strip().lower()
        if not part:
            continue
        h, w = part.split("x")
        out.append((int(h), int(w)))
    if not out:
        raise ValueError(f"no buckets parsed from {s!r}")
    return out


def _run_bucketed(config: VOCSIFTFisherConfig) -> dict:
    """Variable-size ingest track: no global resize — per-bucket static
    shapes through SIFT, descriptors pooled for PCA/GMM, FV rows
    concatenated (``_fisher.fit_fisher_branch_buckets``)."""
    from keystone_tpu.loaders.voc import load_voc_bucketed
    from keystone_tpu.pipelines._fisher import (
        apply_featurizer_buckets,
        fit_fisher_branch_buckets,
    )

    buckets = parse_buckets(config.buckets)
    train = load_voc_bucketed(config.train_location, config.train_labels, buckets)
    test = load_voc_bucketed(config.test_location, config.test_labels, buckets)
    num_classes = VOC_NUM_CLASSES

    results: dict = {}
    with use_mesh(get_mesh()), Timer("VOCSIFTFisher.pipeline") as total:
        gray = [
            (hw, GrayScaler()(jnp.asarray(imgs))[..., 0]) for hw, imgs, _ in train
        ]
        extractor = SIFTExtractor(scales=config.sift_scales)
        featurizer, train_feats, desc_counts = fit_fisher_branch_buckets(
            extractor,
            gray,
            config.desc_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            row_chunks=config.row_chunks,
            gmm_n_init=config.gmm_n_init,
        )
        train_labels = jnp.asarray(
            np.concatenate([lb for _, _, lb in train])
        )
        labels = ClassLabelIndicatorsFromIntArrayLabels(num_classes)(train_labels)
        block_size = _resolved_block_size(
            config, int(train_feats.shape[0]), num_classes
        )
        with Timer("fit.block_least_squares"):
            model = BlockLeastSquaresEstimator(
                block_size, 1, config.lam
            ).fit(train_feats, labels)

        with Timer("eval.test_map"):
            test_gray = [
                (hw, GrayScaler()(jnp.asarray(imgs))[..., 0]) for hw, imgs, _ in test
            ]
            test_feats = apply_featurizer_buckets(featurizer, test_gray)
            scores = model(test_feats)
            test_labels = jnp.asarray(
                np.concatenate([lb for _, _, lb in test])
            )
            evaluator = MeanAveragePrecisionEvaluator(num_classes)
            results["test_map"] = evaluator.mean(test_labels, scores)

    results["buckets"] = {
        f"{hw[0]}x{hw[1]}": {"images": int(imgs.shape[0]), "descriptors": dc}
        for (hw, imgs, _), dc in zip(train, desc_counts)
    }
    results["wallclock_s"] = total.elapsed
    logger.info(
        "TEST APs mean: %.4f  buckets: %s", results["test_map"], results["buckets"]
    )
    return results


def run(config: VOCSIFTFisherConfig) -> dict:
    if config.buckets:
        config.validate()  # bucketed ingest is the real-archive path only
        return _run_bucketed(config)
    if config.train_location:
        hw = (config.image_hw, config.image_hw)
        train = load_voc(config.train_location, config.train_labels, hw)
        test = load_voc(config.test_location, config.test_labels, hw)
        num_classes = VOC_NUM_CLASSES
    else:
        train = synthetic_voc_device(
            config.synthetic_train, config.synthetic_classes,
            (config.synthetic_hw, config.synthetic_hw), seed=1,
        )
        test = synthetic_voc_device(
            config.synthetic_test, config.synthetic_classes,
            (config.synthetic_hw, config.synthetic_hw), seed=2,
        )
        num_classes = config.synthetic_classes

    results: dict = {}
    with use_mesh(get_mesh()), Timer("VOCSIFTFisher.pipeline") as total:
        train_imgs = jnp.asarray(train[0])
        # grayscale on device (MultiLabeledImageExtractor→PixelScaler→
        # GrayScaler, VOCSIFTFisher.scala:36; images are already [0,1])
        gray = GrayScaler()(train_imgs)[..., 0]

        extractor = SIFTExtractor(scales=config.sift_scales)
        gmm_files = (
            (config.gmm_mean_file, config.gmm_var_file, config.gmm_wts_file)
            if config.gmm_mean_file
            else None
        )
        featurizer, train_feats = fit_fisher_branch(
            extractor,
            gray,
            config.desc_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            pca_file=config.pca_file or None,
            gmm_files=gmm_files,
            row_chunks=config.row_chunks,
            gmm_n_init=config.gmm_n_init,
        )

        labels = ClassLabelIndicatorsFromIntArrayLabels(num_classes)(
            jnp.asarray(train[1])
        )
        block_size = _resolved_block_size(
            config, int(train_feats.shape[0]), num_classes
        )
        with Timer("fit.block_least_squares"):
            model = BlockLeastSquaresEstimator(
                block_size, 1, config.lam
            ).fit(train_feats, labels)

        with Timer("eval.test_map"):
            test_gray = GrayScaler()(jnp.asarray(test[0]))[..., 0]
            test_feats = featurizer(test_gray)
            from keystone_tpu.core.cache import get_cache as _get_cache

            from keystone_tpu.utils import knobs as _knobs

            if (
                _get_cache() is not None
                and _knobs.get("KEYSTONE_EVAL_CACHED_TIMING")
            ):
                # cached-vs-cold eval featurization evidence (bench rows
                # ONLY — the env flag keeps ordinary cache-enabled runs
                # from paying a second featurization): the call above
                # stored the whole-chain key; this one must return the
                # stored features without re-featurizing
                import time as _time

                import jax as _jax

                test_feats = _jax.block_until_ready(test_feats)
                t0 = _time.perf_counter()
                _jax.block_until_ready(featurizer(test_gray))
                results["featurize_cached_s"] = round(
                    _time.perf_counter() - t0, 3
                )
            scores = model(test_feats)
            evaluator = MeanAveragePrecisionEvaluator(num_classes)
            results["test_map"] = evaluator.mean(jnp.asarray(test[1]), scores)

    results["wallclock_s"] = total.elapsed
    logger.info("TEST APs mean: %.4f", results["test_map"])
    return results


def main(argv=None):
    print(json.dumps(run(parse_config(VOCSIFTFisherConfig, argv, prog="VOCSIFTFisher"))))


if __name__ == "__main__":
    main()
