"""VOCSIFTFisher: SIFT → PCA → GMM → FisherVector → block least squares →
mean average precision.

Reference: ``pipelines/images/voc/VOCSIFTFisher.scala:18-158`` (defaults:
blockSize 4096, descDim 80, vocabSize 256, 1e6 samples, ``:109-123``).
"""

from __future__ import annotations

import dataclasses
import json

import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.config import parse_config
from keystone_tpu.core.pipeline import chain
from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.loaders.voc import VOC_NUM_CLASSES, load_voc, synthetic_voc_device
from keystone_tpu.ops.images import GrayScaler, SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntArrayLabels
from keystone_tpu.pipelines._fisher import fit_fisher_branch
from keystone_tpu.parallel import get_mesh, use_mesh
from keystone_tpu.utils import Timer, get_logger

logger = get_logger("keystone_tpu.pipelines.voc_sift_fisher")


@dataclasses.dataclass
class VOCSIFTFisherConfig:
    train_location: str = ""
    train_labels: str = ""
    test_location: str = ""
    test_labels: str = ""
    desc_dim: int = 80
    vocab_size: int = 256
    num_pca_samples: int = 1000000
    num_gmm_samples: int = 1000000
    lam: float = 0.5
    # Solver column block size. 0 = auto (core/plan.py precedence:
    # explicitly-set value > KEYSTONE_BLOCK_SIZE env > HBM-budget-planned
    # under KEYSTONE_OPTIMIZER > the hand-tuned 4096).
    block_size: int = 0
    sift_scales: int = 4
    image_hw: int = 256
    # size-bucketed variable-shape ingest: comma-separated HxW ladder (e.g.
    # "128x128,192x256,256x256"). Images land in the smallest containing
    # bucket (pad, no resize) and every extractor stage compiles once per
    # bucket shape — the reference's native-size processing
    # (loaders/ImageLoaderUtils.scala:47-93) under XLA static shapes. Empty
    # -> single-frame ingest at image_hw. Real-archive paths only.
    buckets: str = ""
    pca_file: str = ""
    gmm_mean_file: str = ""
    gmm_var_file: str = ""
    gmm_wts_file: str = ""
    seed: int = 42
    # synthetic fallback (zero-egress environments)
    synthetic_train: int = 256
    synthetic_test: int = 128
    synthetic_classes: int = 8
    synthetic_hw: int = 96
    # row-chunk the extractor/FV stages (ChunkedMap) — needed at reference
    # scale (5k imgs × vocab 256) to bound per-image intermediates
    row_chunks: int = 1
    # independent GMM-EM restarts; best likelihood wins (density-fit tool —
    # see BASELINE.md on why it does not stabilize classifier quality)
    gmm_n_init: int = 1
    # Streaming ingest (real archives only): decoded batches flow straight
    # from the bounded core/ingest.py pipeline into per-batch SIFT+FV
    # featurization — the raw image tensor never exists; only the (n, d_fv)
    # Fisher features are resident (``fit_streaming_ingest``).
    ingest: bool = False
    ingest_batch: int = 128  # images per decoded batch
    sample_images: int = 1024  # prefix images whose descriptors seed PCA/GMM

    def validate(self):
        if self.buckets and not self.train_location:
            raise ValueError(
                "--buckets is variable-size ingest for real archives; the "
                "synthetic generator emits one size (drop --buckets or set "
                "--train-location)"
            )
        if self.ingest:
            if not (self.train_location and self.test_location):
                raise ValueError(
                    "--ingest streams real tar archives (core/ingest.py); "
                    "set --train-location/--test-location"
                )
            if self.buckets:
                raise ValueError(
                    "--ingest decodes into one fixed frame (image_hw); "
                    "combining it with --buckets is not supported yet"
                )


def _resolved_block_size(config: VOCSIFTFisherConfig, n_rows: int,
                         num_classes: int) -> int:
    """Planner-derived solver block size (core/plan.py::resolve_block_size
    precedence; with ``KEYSTONE_OPTIMIZER=0`` this is exactly the prior
    hand-tuned 4096 unless the config/env set one explicitly)."""
    from keystone_tpu.core import plan

    return plan.resolve_block_size(
        "voc.block_solver", explicit=config.block_size or None,
        n_rows=n_rows, num_classes=num_classes, default=4096,
        quantum=max(128, config.desc_dim),
        ceiling=2 * config.desc_dim * config.vocab_size,
    )


def small_config(**overrides) -> VOCSIFTFisherConfig:
    """The BASELINE.md small-config row (1024/256 imgs 96², vocab 16) —
    ONE definition shared by ``bench.py`` and ``scripts/cpu_baseline.py``
    so the TPU/CPU sides of ``voc_small_vs_cpu_baseline`` can never drift
    apart."""
    cfg = dict(
        synthetic_train=1024, synthetic_test=256, vocab_size=16,
        num_pca_samples=1000000, num_gmm_samples=1000000,
    )
    cfg.update(overrides)
    return VOCSIFTFisherConfig(**cfg)


def check_graph():
    """Pipeline contracts for `keystone-tpu check`: the full VOC branch —
    gray → squeeze → SIFT → PCA → FV encode → normalize — at contract
    dims (PCA/GMM weights are zero placeholders; only shapes propagate),
    plus the block-solver fit/apply pair."""
    import jax

    from jax.sharding import PartitionSpec as P

    from keystone_tpu.analysis.check import FitApply, PipelineContract
    from keystone_tpu.core.pipeline import Transformer, chain as _chain
    from keystone_tpu.learning.gmm import GaussianMixtureModel
    from keystone_tpu.learning.pca import BatchPCATransformer
    from keystone_tpu.pipelines._fisher import fisher_featurizer

    desc_dim, vocab = 16, 4
    gmm = GaussianMixtureModel(
        means=jnp.zeros((vocab, desc_dim), jnp.float32),
        variances=jnp.ones((vocab, desc_dim), jnp.float32),
        weights=jnp.ones((vocab,), jnp.float32) / vocab,
    )
    squeeze = Transformer.from_fn(lambda im: im[..., 0], name="squeeze_gray")
    pipe = _chain(
        GrayScaler(), squeeze, SIFTExtractor(scales=2),
        BatchPCATransformer(pca_mat=jnp.zeros((128, desc_dim), jnp.float32)),
        fisher_featurizer(gmm),
    )
    sample = jax.ShapeDtypeStruct((2, 64, 64, 3), jnp.float32)
    # independent traces of the fitted featurizer at train vs test batch
    # sizes (the eval path calls the SAME featurizer chain; C3 guards
    # batch-dependent shape logic)
    return [PipelineContract(
        name="voc.fisher_branch",
        pipe=pipe,
        sample=sample,
        spec=P("data", None, None, None),
        fit_apply=[FitApply(
            "block_least_squares",
            fit_aval=jax.eval_shape(pipe.apply_batch, sample),
            apply_aval=jax.eval_shape(
                pipe.apply_batch,
                jax.ShapeDtypeStruct((1, 64, 64, 3), jnp.float32),
            ),
        )],
    )]


def parse_buckets(s: str):
    """``"128x128,192x256"`` -> ``[(128, 128), (192, 256)]``."""
    out = []
    for part in s.split(","):
        part = part.strip().lower()
        if not part:
            continue
        h, w = part.split("x")
        out.append((int(h), int(w)))
    if not out:
        raise ValueError(f"no buckets parsed from {s!r}")
    return out


def _run_bucketed(config: VOCSIFTFisherConfig) -> dict:
    """Variable-size ingest track: no global resize — per-bucket static
    shapes through SIFT, descriptors pooled for PCA/GMM, FV rows
    concatenated (``_fisher.fit_fisher_branch_buckets``)."""
    from keystone_tpu.loaders.voc import load_voc_bucketed
    from keystone_tpu.pipelines._fisher import (
        apply_featurizer_buckets,
        fit_fisher_branch_buckets,
    )

    buckets = parse_buckets(config.buckets)
    train = load_voc_bucketed(config.train_location, config.train_labels, buckets)
    test = load_voc_bucketed(config.test_location, config.test_labels, buckets)
    num_classes = VOC_NUM_CLASSES

    results: dict = {}
    with use_mesh(get_mesh()), Timer("VOCSIFTFisher.pipeline") as total:
        gray = [
            (hw, GrayScaler()(jnp.asarray(imgs))[..., 0]) for hw, imgs, _ in train
        ]
        extractor = SIFTExtractor(scales=config.sift_scales)
        featurizer, train_feats, desc_counts = fit_fisher_branch_buckets(
            extractor,
            gray,
            config.desc_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            row_chunks=config.row_chunks,
            gmm_n_init=config.gmm_n_init,
        )
        train_labels = jnp.asarray(
            np.concatenate([lb for _, _, lb in train])
        )
        labels = ClassLabelIndicatorsFromIntArrayLabels(num_classes)(train_labels)
        block_size = _resolved_block_size(
            config, int(train_feats.shape[0]), num_classes
        )
        with Timer("fit.block_least_squares"):
            model = BlockLeastSquaresEstimator(
                block_size, 1, config.lam
            ).fit(train_feats, labels)

        with Timer("eval.test_map"):
            test_gray = [
                (hw, GrayScaler()(jnp.asarray(imgs))[..., 0]) for hw, imgs, _ in test
            ]
            test_feats = apply_featurizer_buckets(featurizer, test_gray)
            scores = model(test_feats)
            test_labels = jnp.asarray(
                np.concatenate([lb for _, _, lb in test])
            )
            evaluator = MeanAveragePrecisionEvaluator(num_classes)
            results["test_map"] = evaluator.mean(test_labels, scores)

    results["buckets"] = {
        f"{hw[0]}x{hw[1]}": {"images": int(imgs.shape[0]), "descriptors": dc}
        for (hw, imgs, _), dc in zip(train, desc_counts)
    }
    results["wallclock_s"] = total.elapsed
    logger.info(
        "TEST APs mean: %.4f  buckets: %s", results["test_map"], results["buckets"]
    )
    return results


def _run_streaming_ingest(config: VOCSIFTFisherConfig) -> dict:
    """Never-resident VOC fit: decoded batches stream from the bounded
    ingest pipeline (``core/ingest.py``) into one fixed-shape jitted
    gray→SIFT→PCA→FV program per batch. Only the (n, 2·desc_dim·vocab)
    Fisher features — the solver's input — are ever resident; raw images
    live only inside the recycled host buffer ring. Pass A streams a
    prefix of the archive for the PCA/GMM descriptor sample; pass B
    re-streams everything and featurizes batch-by-batch."""
    import jax

    from keystone_tpu.core.ingest import (
        StreamingTarIngest,
        ingest_buffers,
        stream_batches,
    )
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.learning.pca import PCAEstimator
    from keystone_tpu.loaders.voc import (
        labels_for_name,
        load_voc_labels,
        pad_label_lists,
    )
    from keystone_tpu.ops.stats import ColumnSampler
    from keystone_tpu.pipelines._fisher import fisher_featurizer

    results: dict = {}
    bs = config.ingest_batch
    hw = (config.image_hw, config.image_hw)
    num_classes = VOC_NUM_CLASSES
    extractor = SIFTExtractor(scales=config.sift_scales)

    def gray_descs(imgs):
        return extractor(GrayScaler()(imgs)[..., 0])

    @jax.jit
    def _batch_descs(imgs):
        return gray_descs(imgs)

    def labeled_rows(names, n, labels_map):
        """(row indices, their label lists) for entries present in the CSV
        (the shared ``labels_for_name`` match rule, as ``load_voc``)."""
        rows, labels = [], []
        for i, name in enumerate(names[:n]):
            ls = labels_for_name(labels_map, name)
            if ls is not None:
                rows.append(i)
                labels.append(ls)
        return rows, labels

    def stream(location):
        return stream_batches(StreamingTarIngest([location], hw, bs))

    with use_mesh(get_mesh()), Timer("VOCSIFTFisher.streaming_ingest") as total:
        train_map = load_voc_labels(config.train_labels)
        # Pass A: descriptor sample from the archive's first labeled images
        parts, seen = [], 0
        for imgs, names, n in stream(config.train_location):
            rows, _ = labeled_rows(names, n, train_map)
            if not rows:
                continue
            descs = _batch_descs(imgs)
            parts.append(descs[jnp.asarray(rows, jnp.int32)])
            seen += len(rows)
            if seen >= config.sample_images:
                break
        if not parts:
            raise ValueError(
                f"no images in {config.train_location} matched the "
                f"{len(train_map)} filenames in {config.train_labels}"
            )
        sample = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        del parts
        with Timer("fisher.fit_pca"):
            pca = PCAEstimator(config.desc_dim).fit_batch(
                ColumnSampler(config.num_pca_samples, seed=config.seed)(sample)
            )
        with Timer("fisher.fit_gmm"):
            gmm = GaussianMixtureModelEstimator(
                config.vocab_size, n_init=config.gmm_n_init
            ).fit(ColumnSampler(
                config.num_gmm_samples, seed=config.seed + 1
            )(pca(sample)))
        del sample
        fisher = fisher_featurizer(gmm)

        # ONE compiled program per decoded batch: extract + PCA + FV encode
        # at the fixed (ingest_batch, H, W, 3) ring shape — zero
        # steady-state recompiles (``ingest_featurize_compiles``).
        @jax.jit
        def _featurize(imgs, pca_mat):
            return fisher(gray_descs(imgs) @ pca_mat)

        def featurize_stream(location, labels_map):
            feat_parts, label_lists = [], []
            for imgs, names, n in stream(location):
                rows, labels = labeled_rows(names, n, labels_map)
                if not rows:
                    continue
                F = _featurize(imgs, pca.pca_mat)
                feat_parts.append(F[jnp.asarray(rows, jnp.int32)])
                label_lists.extend(labels)
            if not feat_parts:
                raise ValueError(f"no labeled images streamed from {location}")
            feats = (jnp.concatenate(feat_parts)
                     if len(feat_parts) > 1 else feat_parts[0])
            return feats, pad_label_lists(label_lists)

        with Timer("streaming.featurize_train"):
            train_feats, train_labels = featurize_stream(
                config.train_location, train_map
            )
        labels = ClassLabelIndicatorsFromIntArrayLabels(num_classes)(
            jnp.asarray(train_labels)
        )
        block_size = _resolved_block_size(
            config, int(train_feats.shape[0]), num_classes
        )
        with Timer("fit.block_least_squares"):
            model = BlockLeastSquaresEstimator(
                block_size, 1, config.lam
            ).fit(train_feats, labels)

        with Timer("eval.test_map"):
            test_feats, test_labels = featurize_stream(
                config.test_location, load_voc_labels(config.test_labels)
            )
            scores = model(test_feats)
            evaluator = MeanAveragePrecisionEvaluator(num_classes)
            results["test_map"] = evaluator.mean(
                jnp.asarray(test_labels), scores
            )

    frame_bytes = hw[0] * hw[1] * 3 * 4
    n_total = int(train_feats.shape[0]) + int(test_feats.shape[0])
    results["wallclock_s"] = total.elapsed
    results["ingest_images"] = n_total
    results["ingest_raw_bytes"] = int(n_total * frame_bytes)
    results["ingest_peak_host_bytes"] = int(ingest_buffers() * bs * frame_bytes)
    results["ingest_featurize_compiles"] = int(_featurize._cache_size())
    logger.info(
        "streaming-ingest TEST APs mean: %.4f  (raw %.1f MB through a "
        "%.1f MB ring)", results["test_map"],
        results["ingest_raw_bytes"] / 1e6,
        results["ingest_peak_host_bytes"] / 1e6,
    )
    return results


def fit_streaming_ingest(config: VOCSIFTFisherConfig) -> dict:
    """Public entry for the never-resident streaming-ingest VOC fit (the
    ``--ingest`` path of :func:`run`)."""
    import dataclasses as _dc

    if not config.ingest:
        config = _dc.replace(config, ingest=True)
    config.validate()
    return _run_streaming_ingest(config)


def run(config: VOCSIFTFisherConfig) -> dict:
    if config.ingest:
        config.validate()
        return _run_streaming_ingest(config)
    if config.buckets:
        config.validate()  # bucketed ingest is the real-archive path only
        return _run_bucketed(config)
    if config.train_location:
        hw = (config.image_hw, config.image_hw)
        train = load_voc(config.train_location, config.train_labels, hw)
        test = load_voc(config.test_location, config.test_labels, hw)
        num_classes = VOC_NUM_CLASSES
    else:
        train = synthetic_voc_device(
            config.synthetic_train, config.synthetic_classes,
            (config.synthetic_hw, config.synthetic_hw), seed=1,
        )
        test = synthetic_voc_device(
            config.synthetic_test, config.synthetic_classes,
            (config.synthetic_hw, config.synthetic_hw), seed=2,
        )
        num_classes = config.synthetic_classes

    results: dict = {}
    with use_mesh(get_mesh()), Timer("VOCSIFTFisher.pipeline") as total:
        train_imgs = jnp.asarray(train[0])
        # grayscale on device (MultiLabeledImageExtractor→PixelScaler→
        # GrayScaler, VOCSIFTFisher.scala:36; images are already [0,1])
        gray = GrayScaler()(train_imgs)[..., 0]

        extractor = SIFTExtractor(scales=config.sift_scales)
        gmm_files = (
            (config.gmm_mean_file, config.gmm_var_file, config.gmm_wts_file)
            if config.gmm_mean_file
            else None
        )
        featurizer, train_feats = fit_fisher_branch(
            extractor,
            gray,
            config.desc_dim,
            config.vocab_size,
            config.num_pca_samples,
            config.num_gmm_samples,
            seed=config.seed,
            pca_file=config.pca_file or None,
            gmm_files=gmm_files,
            row_chunks=config.row_chunks,
            gmm_n_init=config.gmm_n_init,
        )

        labels = ClassLabelIndicatorsFromIntArrayLabels(num_classes)(
            jnp.asarray(train[1])
        )
        block_size = _resolved_block_size(
            config, int(train_feats.shape[0]), num_classes
        )
        with Timer("fit.block_least_squares"):
            model = BlockLeastSquaresEstimator(
                block_size, 1, config.lam
            ).fit(train_feats, labels)

        with Timer("eval.test_map"):
            test_gray = GrayScaler()(jnp.asarray(test[0]))[..., 0]
            test_feats = featurizer(test_gray)
            from keystone_tpu.core.cache import get_cache as _get_cache

            from keystone_tpu.utils import knobs as _knobs

            if (
                _get_cache() is not None
                and _knobs.get("KEYSTONE_EVAL_CACHED_TIMING")
            ):
                # cached-vs-cold eval featurization evidence (bench rows
                # ONLY — the env flag keeps ordinary cache-enabled runs
                # from paying a second featurization): the call above
                # stored the whole-chain key; this one must return the
                # stored features without re-featurizing
                import time as _time

                import jax as _jax

                test_feats = _jax.block_until_ready(test_feats)
                t0 = _time.perf_counter()
                _jax.block_until_ready(featurizer(test_gray))
                results["featurize_cached_s"] = round(
                    _time.perf_counter() - t0, 3
                )
            scores = model(test_feats)
            evaluator = MeanAveragePrecisionEvaluator(num_classes)
            results["test_map"] = evaluator.mean(jnp.asarray(test[1]), scores)

    results["wallclock_s"] = total.elapsed
    logger.info("TEST APs mean: %.4f", results["test_map"])
    return results


def main(argv=None):
    print(json.dumps(run(parse_config(VOCSIFTFisherConfig, argv, prog="VOCSIFTFisher"))))


if __name__ == "__main__":
    main()
