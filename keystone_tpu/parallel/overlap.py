"""Latency-hiding collectives for the block solvers.

The solver stack's reductions — per-block gram matrices and cross terms over
row-sharded data — lower by default to one bulk ICI all-reduce *after* the
MXU matmul finishes: none of the collective time hides behind compute, the
exact serialization "Large Scale Distributed Linear Algebra With Tensor
Processing Units" (PAPERS.md) shows must be pipelined to reach roofline, and
the treeReduce bottleneck KeystoneML inherited from Spark. This module is
the pipelined alternative, opt-in via one knob:

- :func:`tiled_transpose_matmul` — the **collective matmul**: ``XᵀY`` with
  rows sharded, the output's feature axis chunked into tiles. Tile *t*'s
  partial product is reduced with ``lax.psum_scatter`` while the MXU is
  already multiplying tile *t+1* — k per-tile reduce-scatters the scheduler
  can overlap, instead of a single terminal all-reduce it cannot. One
  trailing ``all_gather`` re-assembles the replicated result (the same total
  wire bytes as the all-reduce, but the reduce half rides under compute).

- :func:`tiled_psum_dot` — the same tiling for use *inside* an existing
  ``shard_map`` body (the TSQR tree's ``Qᵀb`` reduction).

- :func:`bidirectional_ring_gram` — the feature-sharded ring gram
  (``parallel/ring.py::ring_gram``) rotating blocks in BOTH ring directions
  via paired ``ppermute``s: ⌈(k-1)/2⌉ rounds instead of k-1, both ICI links
  busy every step, each block travelling at most half the ring. Tiles are
  computed by the same matmul on the same operands as the unidirectional
  schedule, so the result is bit-identical.

The knob mirrors the cache layer (``core/cache.py``): ``KEYSTONE_OVERLAP=1``
in the environment, ``use_overlap(True)`` as a context, or ``overlap=`` on
any solver entry point — per-call beats context beats env. Everything
degrades gracefully: with no mesh, a trivial mesh axis, or shapes the tiling
cannot divide, callers fall back to the monolithic ``hdot`` path
(:func:`maybe_tiled_transpose_matmul`), so the knob is always safe to set.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from keystone_tpu.linalg.solvers import hdot

_OVERLAP_STACK: list = []


def overlap_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the overlap knob: per-call ``override`` beats the innermost
    :func:`use_overlap` context beats the ``KEYSTONE_OVERLAP`` env var
    (default off — the pipelined path is opt-in, like the cache)."""
    if override is not None:
        return bool(override)
    if _OVERLAP_STACK:
        return _OVERLAP_STACK[-1]
    return os.environ.get("KEYSTONE_OVERLAP", "0") == "1"


@contextlib.contextmanager
def use_overlap(flag: bool):
    """Scope the overlap knob (the ``use_cache`` pattern)."""
    _OVERLAP_STACK.append(bool(flag))
    try:
        yield
    finally:
        _OVERLAP_STACK.pop()


def overlap_mesh(
    override: Optional[bool] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> Optional[Mesh]:
    """The mesh to pipeline over, or None when overlap should not run:
    knob off, no usable mesh, or a trivial (size-1) axis — a single chip has
    no collective to hide. The returned mesh is hashable, so solvers thread
    it through ``jax.jit`` as a static argument (the overlap decision changes
    program structure and must never be a traced value)."""
    if not overlap_enabled(override):
        return None
    if mesh is None:
        from keystone_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
    if axis not in mesh.shape or mesh.shape[axis] <= 1:
        return None
    return mesh


def _pick_tiles(dim: int, k: int, target: Optional[int] = None) -> int:
    """Largest tile count ≤ ``target`` (default: the axis size, so the
    pipelined program carries ≥ k per-tile collectives when shapes allow)
    such that ``dim`` splits into equal tiles each divisible by ``k``
    (``psum_scatter`` scatters tile rows over the k shards). 0 = no valid
    tiling (callers fall back to the monolithic reduction)."""
    if dim % k:
        return 0
    target = target or max(k, 1)
    for t in range(min(target, dim // k), 0, -1):
        if dim % (t * k) == 0:
            return t
    return 0


def tiled_transpose_matmul(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
) -> jax.Array:
    """Replicated ``XᵀY`` (``y=None`` → the gram ``XᵀX``) for row-sharded
    operands, as a tiled reduce-scatter collective matmul.

    ``x``: (n, dx), ``y``: (n, dy), rows sharded over ``axis``. The output's
    dx rows are chunked into ``tiles`` tiles; per tile, the local partial
    ``x_tileᵀ y`` is ``psum_scatter``-reduced (scattering the tile's rows
    over the k shards) so the reduction of tile *t* overlaps the matmul of
    tile *t+1*; one trailing ``all_gather`` + reorder replicates the result.
    Raises ``ValueError`` when n or dx cannot be divided — use
    :func:`maybe_tiled_transpose_matmul` for the silently-falling-back form.
    """
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    k = mesh.shape[axis]
    y = x if y is None else y
    n, dx = x.shape
    if y.shape[0] != n:
        raise ValueError(f"row mismatch: x has {n} rows, y has {y.shape[0]}")
    if n % k:
        raise ValueError(
            f"row count {n} must be divisible by the '{axis}' axis size {k}"
        )
    T = tiles or _pick_tiles(dx, k)
    if T == 0 or dx % (T * k):
        raise ValueError(
            f"feature dim {dx} cannot be tiled {tiles or '(auto)'}-way over "
            f"the '{axis}' axis size {k}: need dim % (tiles*k) == 0"
        )

    def local(xi, yi):
        # one shared tiling implementation (tiled_psum_dot): rows of xi.T
        # are xi's feature columns, so this is exactly the per-tile
        # psum_scatter + trailing all_gather schedule; divisibility was
        # validated above, so the monolithic-psum fallback cannot trigger.
        return tiled_psum_dot(xi.T, yi, axis, tiles=T, precision=precision)

    spec = P(axis, None)
    # check_vma=False: the all_gather + identical reorder makes the output
    # replicated by construction; the static checker can't see that.
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec), out_specs=P(), check_vma=False
    )(x, y)


def maybe_tiled_transpose_matmul(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
) -> jax.Array:
    """:func:`tiled_transpose_matmul` when the mesh/shapes allow it, else the
    monolithic ``hdot`` (whose row contraction XLA all-reduces). All checks
    run at trace time — shapes are static — so inside a jitted solver body
    this picks ONE path per compiled program, never a runtime branch."""
    yy = x if y is None else y
    if (
        mesh is None
        or axis not in mesh.shape
        or mesh.shape[axis] <= 1
        or x.ndim != 2
        or yy.ndim != 2
        or x.shape[0] % mesh.shape[axis]
        or _pick_tiles(x.shape[1], mesh.shape[axis], tiles) == 0
    ):
        return hdot(x.T, yy, precision)
    return tiled_transpose_matmul(
        x, yy, mesh=mesh, axis=axis, tiles=tiles, precision=precision
    )


def tiled_psum_dot(
    a: jax.Array,
    b: jax.Array,
    axis: str,
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
) -> jax.Array:
    """``psum(a @ b)`` over ``axis`` for use INSIDE a ``shard_map`` body,
    tiled so each tile's reduce-scatter overlaps the next tile's matmul
    (the TSQR tree's ``Qᵀb`` reduction). ``a``: (m, p) per-shard partial
    factor, ``b``: (p, c); returns the replicated-by-construction (m, c)
    sum. Falls back to the monolithic ``psum`` when m cannot be tiled."""
    k = jax.lax.axis_size(axis)
    m = a.shape[0]
    T = tiles or _pick_tiles(m, k)
    if k <= 1 or T == 0 or m % (T * k):
        return jax.lax.psum(hdot(a, b, precision), axis)
    tb = m // T
    pb = tb // k
    c = b.shape[1]
    pieces = [
        jax.lax.psum_scatter(
            hdot(a[t * tb : (t + 1) * tb], b, precision),
            axis,
            scatter_dimension=0,
            tiled=True,
        )
        for t in range(T)
    ]
    full = jax.lax.all_gather(jnp.concatenate(pieces, 0), axis)
    return full.reshape(k, T, pb, c).transpose(1, 0, 2, 3).reshape(m, c)


def bidirectional_ring_gram(
    x: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "model",
    precision: str = "highest",
) -> jax.Array:
    """``XᵀX`` with the feature axis sharded over ``axis`` — the
    bidirectional schedule of ``ring.ring_gram``.

    Two copies of the resident column block circulate the ring in opposite
    directions via PAIRED ``ppermute``s: after round t, the forward copy on
    device j holds block j-t and the backward copy block j+t, so each round
    fills TWO gram tiles and the ring completes in ⌈(k-1)/2⌉ rounds instead
    of k-1 — both ICI links carry traffic every step and each block travels
    at most half the ring (half the per-link wire time of the unidirectional
    rotation). Every tile is the same ``hdot`` on the same operands as the
    unidirectional schedule, so the output is bit-identical to
    ``ring_gram(..., bidirectional=False)``.

    The rounds are unrolled (k is static and small): the compiled HLO shows
    the paired collective-permutes per round — the structure the comm-pattern
    tests pin — and gives the scheduler independent permute/matmul chains to
    overlap. Odd k needs no special case; even k has one unpaired middle
    block (distance k/2, reachable equally from either direction) folded via
    a single final forward hop.
    """
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    k = mesh.shape[axis]
    d = x.shape[1]
    if d % k:
        raise ValueError(
            f"feature dim {d} must be divisible by the '{axis}' axis size {k}"
        )
    db = d // k
    fwd_perm = [(i, (i + 1) % k) for i in range(k)]  # j receives from j-1
    bwd_perm = [(i, (i - 1) % k) for i in range(k)]  # j receives from j+1

    def local(xj):
        j = jax.lax.axis_index(axis)

        def fold(src, visiting, out):
            tile = hdot(visiting.T, xj, precision)  # (db, db): X_srcᵀ X_j
            return jax.lax.dynamic_update_slice(out, tile, (src * db, 0))

        out = jax.lax.pcast(jnp.zeros((d, db), xj.dtype), axis, to="varying")
        out = fold(j, xj, out)  # own tile, no hop
        fwd = bwd = xj
        for t in range(1, (k - 1) // 2 + 1):
            fwd = jax.lax.ppermute(fwd, axis, fwd_perm)
            bwd = jax.lax.ppermute(bwd, axis, bwd_perm)
            out = fold((j - t) % k, fwd, out)
            out = fold((j + t) % k, bwd, out)
        if k % 2 == 0 and k > 1:
            # unpaired middle block at distance k/2: one more forward hop
            fwd = jax.lax.ppermute(fwd, axis, fwd_perm)
            out = fold((j - k // 2) % k, fwd, out)
        return out

    spec = P(None, axis)
    return jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)(x)
