"""Latency-hiding collectives for the block solvers.

The solver stack's reductions — per-block gram matrices and cross terms over
row-sharded data — lower by default to one bulk ICI all-reduce *after* the
MXU matmul finishes: none of the collective time hides behind compute, the
exact serialization "Large Scale Distributed Linear Algebra With Tensor
Processing Units" (PAPERS.md) shows must be pipelined to reach roofline, and
the treeReduce bottleneck KeystoneML inherited from Spark. This module is
the pipelined alternative, opt-in via one knob:

- :func:`tiled_transpose_matmul` — the **collective matmul**: ``XᵀY`` with
  rows sharded, the output's feature axis chunked into tiles. Tile *t*'s
  partial product is reduced with ``lax.psum_scatter`` while the MXU is
  already multiplying tile *t+1* — k per-tile reduce-scatters the scheduler
  can overlap, instead of a single terminal all-reduce it cannot. One
  trailing ``all_gather`` re-assembles the replicated result (the same total
  wire bytes as the all-reduce, but the reduce half rides under compute).

- :func:`tiled_psum_dot` — the same tiling for use *inside* an existing
  ``shard_map`` body (the TSQR tree's ``Qᵀb`` reduction).

- :func:`bidirectional_ring_gram` — the feature-sharded ring gram
  (``parallel/ring.py::ring_gram``) rotating blocks in BOTH ring directions
  via paired ``ppermute``s: ⌈(k-1)/2⌉ rounds instead of k-1, both ICI links
  busy every step, each block travelling at most half the ring. Tiles are
  computed by the same matmul on the same operands as the unidirectional
  schedule, so the result is bit-identical.

Topology-aware extensions (the second layer on top of the tiling):

- **Two-tier ICI/DCN reduce-scatter** — on multi-slice meshes the sharded
  axis is not uniform: within-slice hops ride ICI, cross-slice hops ride
  DCN (an order of magnitude less bandwidth). :func:`mesh_tiers` probes the
  slice structure from ``jax.devices()`` (``KEYSTONE_MESH_TIERS`` overrides)
  and :func:`tiled_psum_dot` splits each tile's reduction into an inner
  within-slice ``psum_scatter`` (ICI) plus an outer cross-slice exchange
  that ships only the already-reduced slice partials (1/inner of the bytes)
  over DCN — batched over several inner tiles (per-tier tile sizes) so each
  slow DCN exchange hides behind more MXU work than one ICI tile buys.

- :func:`ring_tsqr_fold` — the overlapped TSQR R-tree: instead of one bulk
  ``all_gather`` of the per-shard R factors followed by one monolithic
  second-level QR, the (R_i, Qᵢᵀb_i) pairs circulate the ring in both
  directions via paired ``ppermute``s and each arrival is folded into a
  running QR panel factorization — the per-round permute hides behind the
  previous round's panel QR, and the Qᵀb rotation rides through the same
  fold (no separate psum at all).

- :func:`model_tiled_transpose_matmul` — the column-sharded
  (``P('data','model')``) regime: the model-axis block rotation of
  :func:`bidirectional_ring_gram` composed with the data-axis tile loop, so
  the 256k-dim BCD blocks' gram/cross reductions overlap on BOTH axes.

The knob mirrors the cache layer (``core/cache.py``): ``KEYSTONE_OVERLAP=1``
in the environment, ``use_overlap(True)`` as a context, or ``overlap=`` on
any solver entry point — per-call beats context beats env. Tile counts come
from :func:`_pick_tiles` (``KEYSTONE_OVERLAP_TILES`` overrides per-topology).
Everything degrades gracefully: with no mesh, a trivial mesh axis, or shapes
the tiling cannot divide, callers fall back to the monolithic ``hdot`` path
(:func:`maybe_tiled_transpose_matmul`) — and since a silently-fallen-back
flagship run is indistinguishable from an overlapped one in bench output,
every such fallback is logged once per call-site/shape via ``logging``.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.linalg.solvers import hdot
from keystone_tpu.parallel.ring import bidirectional_rounds, paired_ring_perms
from keystone_tpu.utils import knobs

_OVERLAP_STACK: list = []

# One warning per (site, detail) for the life of the process: the fallback
# is a trace-time decision that re-fires on every solver call with the same
# shapes, and a log line per block×iteration would drown the run.
# Concurrent fits (the prefetch feed traces from its own thread) hit this
# set simultaneously, hence the lock.
_FALLBACK_LOGGED: set = set()
_fallback_lock = threading.Lock()


def _count(event: str, value: float = 1, **labels) -> None:
    """Record an overlap scheduling decision in the telemetry registry
    (``telemetry/registry.py``). Counters fire where the DECISION is made:
    once per outer call for the eager entry points, once per trace for the
    in-``shard_map`` sites — they count chosen schedules, not device
    executions. Tests and the bench assert engagement/fallback directly
    from these series instead of scraping the rate-limited log."""
    from keystone_tpu.telemetry import get_registry

    get_registry().inc(f"overlap.{event}", value, **labels)


def _log_fallback(site: str, detail: str) -> None:
    """Rate-limited (once per site+shape) warning that an overlap-requested
    reduction fell back to the monolithic collective — without this a
    mis-tiled flagship run looks identical to an overlapped one in the
    bench output. The telemetry counter is NOT rate-limited: every fallback
    decision increments ``overlap.fallback{site=...}``."""
    _count("fallback", site=site)
    key = (site, detail)
    with _fallback_lock:
        if key in _FALLBACK_LOGGED:
            return
        _FALLBACK_LOGGED.add(key)
    from keystone_tpu.utils import get_logger

    get_logger("keystone_tpu.parallel.overlap").warning(
        "overlap fallback at %s: %s — using the monolithic collective "
        "(logged once per shape)", site, detail,
    )


def overlap_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the overlap knob: per-call ``override`` beats the innermost
    :func:`use_overlap` context beats the ``KEYSTONE_OVERLAP`` env var
    (default off — the pipelined path is opt-in, like the cache)."""
    if override is not None:
        return bool(override)
    if _OVERLAP_STACK:
        return _OVERLAP_STACK[-1]
    return knobs.get("KEYSTONE_OVERLAP")


@contextlib.contextmanager
def use_overlap(flag: bool):
    """Scope the overlap knob (the ``use_cache`` pattern).

    The stack is push/pop strictly nested within one thread's with-block;
    cross-thread scoping is not a supported use, so the mutations carry an
    R5 pragma instead of a lock."""
    # lint: disable=R5 (strictly nested per-thread context stack)
    _OVERLAP_STACK.append(bool(flag))
    try:
        yield
    finally:
        # lint: disable=R5 (paired with the push above)
        _OVERLAP_STACK.pop()


def overlap_mesh(
    override: Optional[bool] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> Optional[Mesh]:
    """The mesh to pipeline over, or None when overlap should not run:
    knob off, no usable mesh, or a trivial (size-1) axis — a single chip has
    no collective to hide. The returned mesh is hashable, so solvers thread
    it through ``jax.jit`` as a static argument (the overlap decision changes
    program structure and must never be a traced value)."""
    if not overlap_enabled(override):
        return None
    if mesh is None:
        from keystone_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
    if axis not in mesh.shape or mesh.shape[axis] <= 1:
        _log_fallback(
            "overlap_mesh",
            f"knob on but '{axis}' axis is trivial "
            f"(mesh {dict(mesh.shape)}) — nothing to hide",
        )
        return None
    return mesh


def _env_tiles() -> Tuple[Optional[int], Optional[int]]:
    """Parse ``KEYSTONE_OVERLAP_TILES``: ``"T"`` (inner tile-count target)
    or ``"T,To"`` (inner target, outer/DCN exchange count) — the
    per-topology tuning knob for :func:`_pick_tiles`, so tile counts can be
    tuned without code edits. Returns (None, None) when unset; raises
    ``ValueError`` (from the knob registry's normalizing validator — the
    single place the format is parsed) otherwise."""
    parsed = knobs.get("KEYSTONE_OVERLAP_TILES")
    if parsed is None:
        return None, None
    return parsed


def _autotuned_tiles(dim: int, k: int, tier: str = "f32") -> Optional[int]:
    """Device-keyed autotuner default for the tile-count target
    (``ops/pallas/autotune.py``, kernel id ``overlap.tiles``): a swept
    winner for this (dim, k) shape bucket — and this precision tier; a
    bf16 winner must never serve an f32 schedule or vice versa, so the
    tier joins the bucket key (``autotune.precision_bucket``) — on this
    device generation, or None. Lookup-only — the scheduler itself never
    times; winners are recorded by the ``solver_overlap`` bench regime's
    gram sweep (``scripts/bench_regime.py``, multi-device runs), the
    ``scripts/autotune_sweep.py`` CPU sweep, or pod tooling via
    ``autotune.sweep``/``record``. The resolution order stays:
    explicit ``tiles=`` arg beats the ``KEYSTONE_OVERLAP_TILES`` env
    override beats this default beats the axis-size heuristic."""
    try:
        from keystone_tpu.ops.pallas import autotune

        val = autotune.lookup(
            "overlap.tiles",
            autotune.precision_bucket(autotune.shape_bucket(dim, k), tier),
        )
        return int(val) if val else None
    except Exception:  # tuning must never break a solver schedule
        return None


def _pick_tiles(
    dim: int, k: int, target: Optional[int] = None, tier: str = "f32"
) -> int:
    """Largest tile count ≤ ``target`` (default: the ``KEYSTONE_OVERLAP_TILES``
    env override when set, else the autotuner's device-keyed winner when
    persisted (:func:`_autotuned_tiles`, keyed by shape bucket AND ``tier``),
    else the axis size — so the pipelined program carries ≥ k per-tile
    collectives when shapes allow) such that ``dim`` splits into equal tiles
    each divisible by ``k`` (``psum_scatter`` scatters tile rows over the k
    shards). 0 = no valid tiling (callers fall back to the monolithic
    reduction)."""
    if dim % k:
        return 0
    if target is None:
        target = _env_tiles()[0]
    if target is None:
        target = _autotuned_tiles(dim, k, tier)
    target = target or max(k, 1)
    for t in range(min(target, dim // k), 0, -1):
        if dim % (t * k) == 0:
            return t
    return 0


def mesh_tiers(mesh: Mesh, axis: str = "data") -> Tuple[int, int]:
    """(outer, inner) factorization of the ``axis`` size into communication
    tiers: ``inner`` devices per slice (ICI-connected) × ``outer`` slices
    (connected over DCN). Single-tier meshes return ``(1, k)``.

    Resolution order: ``KEYSTONE_MESH_TIERS=<num_slices>`` (validated:
    must be a positive integer dividing the axis size) beats the probe.
    The probe walks the mesh's devices along ``axis`` and groups them by
    slice identity (``slice_index`` where the platform exposes it, else
    ``process_index`` — one host per slice on multi-host CPU/TPU pods);
    only a clean tiering — equal-length contiguous runs per slice — is
    accepted, anything irregular degrades to single-tier (logged once)."""
    k = mesh.shape[axis]
    raw = (knobs.get_raw("KEYSTONE_MESH_TIERS") or "").strip()
    if raw:
        try:
            outer = int(raw)
        except ValueError:
            outer = -1
        if outer < 1 or k % outer:
            raise ValueError(
                f"KEYSTONE_MESH_TIERS={raw!r} is invalid for the '{axis}' "
                f"axis of size {k}: expected a positive integer number of "
                f"slices dividing {k} (e.g. KEYSTONE_MESH_TIERS=2)"
            )
        return outer, k // outer
    # probe: devices along the axis (first coordinate of every other axis —
    # mesh construction tiles slices identically across the other axes)
    import numpy as np

    idx = list(mesh.axis_names).index(axis)
    devs = np.moveaxis(mesh.devices, idx, 0).reshape(k, -1)[:, 0]
    ids = [getattr(d, "slice_index", None) for d in devs]
    if any(i is None for i in ids):
        ids = [getattr(d, "process_index", 0) for d in devs]
    uniq = []
    for i in ids:  # contiguous-run compression, order-preserving
        if not uniq or uniq[-1] != i:
            uniq.append(i)
    outer = len(uniq)
    if outer <= 1 or len(set(uniq)) != outer or k % outer:
        if outer > 1:
            _log_fallback(
                "mesh_tiers", f"irregular slice layout {ids} on '{axis}'"
            )
        return 1, k
    inner = k // outer
    if any(ids[s * inner] != ids[s * inner + j]
           for s in range(outer) for j in range(inner)):
        _log_fallback(
            "mesh_tiers", f"unequal slice runs {ids} on '{axis}'"
        )
        return 1, k
    return outer, inner


def _tier_groups(outer: int, inner: int):
    """``axis_index_groups`` for the two tiers of a (outer × inner)-tiered
    axis, device axis index i = slice*inner + local: inner groups reduce
    within a slice (ICI), outer groups exchange one-member-per-slice
    partials (DCN)."""
    inner_groups = [
        [s * inner + j for j in range(inner)] for s in range(outer)
    ]
    outer_groups = [
        [s * inner + j for s in range(outer)] for j in range(inner)
    ]
    return inner_groups, outer_groups


def tiled_transpose_matmul(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
    tiers: Optional[Tuple[int, int]] = None,
    tier: str = "f32",
) -> jax.Array:
    """Replicated ``XᵀY`` (``y=None`` → the gram ``XᵀX``) for row-sharded
    operands, as a tiled reduce-scatter collective matmul.

    ``x``: (n, dx), ``y``: (n, dy), rows sharded over ``axis``. The output's
    dx rows are chunked into ``tiles`` tiles; per tile, the local partial
    ``x_tileᵀ y`` is ``psum_scatter``-reduced (scattering the tile's rows
    over the k shards) so the reduction of tile *t* overlaps the matmul of
    tile *t+1*; one trailing ``all_gather`` + reorder replicates the result.
    ``tiers`` (default: :func:`mesh_tiers` — the probe / ``KEYSTONE_MESH_TIERS``)
    engages the two-tier ICI/DCN schedule on multi-slice meshes.
    ``tier="bf16"`` (the ``KEYSTONE_PRECISION_TIER`` storage tier, resolved
    by the caller) stores the per-tile matmul operands in bfloat16 and
    accumulates f32 — the per-tile reductions and the trailing all-gather
    always ride the f32 accumulator outputs, so collectives never carry
    bf16 partial sums.
    Raises ``ValueError`` when n or dx cannot be divided — use
    :func:`maybe_tiled_transpose_matmul` for the silently-falling-back form.
    """
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    k = mesh.shape[axis]
    y = x if y is None else y
    n, dx = x.shape
    if y.shape[0] != n:
        raise ValueError(f"row mismatch: x has {n} rows, y has {y.shape[0]}")
    if n % k:
        raise ValueError(
            f"row count {n} must be divisible by the '{axis}' axis size {k}"
        )
    T = tiles or _pick_tiles(dx, k, tier=tier)
    if T == 0 or dx % (T * k):
        raise ValueError(
            f"feature dim {dx} cannot be tiled {tiles or '(auto)'}-way over "
            f"the '{axis}' axis size {k}: need dim % (tiles*k) == 0"
        )
    tiers = tiers or mesh_tiers(mesh, axis)
    _count(
        "engaged", site="tiled_transpose_matmul",
        schedule="two_tier" if tiers[0] > 1 else "single_tier",
    )

    def local(xi, yi):
        # one shared tiling implementation (tiled_psum_dot): rows of xi.T
        # are xi's feature columns, so this is exactly the per-tile
        # psum_scatter + trailing all_gather schedule; divisibility was
        # validated above, so the monolithic-psum fallback cannot trigger.
        return tiled_psum_dot(
            xi.T, yi, axis, tiles=T, precision=precision, tiers=tiers,
            tier=tier,
        )

    spec = P(axis, None)
    # check_vma=False: the all_gather + identical reorder makes the output
    # replicated by construction; the static checker can't see that.
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec), out_specs=P(), check_vma=False
    )(x, y)


def maybe_tiled_transpose_matmul(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
    tier: str = "f32",
) -> jax.Array:
    """:func:`tiled_transpose_matmul` when the mesh/shapes allow it, else the
    monolithic ``hdot`` (whose row contraction XLA all-reduces). All checks
    run at trace time — shapes are static — so inside a jitted solver body
    this picks ONE path per compiled program, never a runtime branch.
    A shape-driven fallback on a live overlap mesh is logged once per shape
    (:func:`_log_fallback`) so a mis-tiled run is visible in the log.
    ``tier`` (the caller-resolved storage dtype tier) applies on BOTH paths
    — a fallback must not silently lose the bf16 storage the caller asked
    for."""
    yy = x if y is None else y
    if (
        mesh is None
        or axis not in mesh.shape
        or mesh.shape[axis] <= 1
        or x.ndim != 2
        or yy.ndim != 2
    ):
        return hdot(x.T, yy, precision, tier=tier)
    k = mesh.shape[axis]
    if x.shape[0] % k:
        _log_fallback(
            "maybe_tiled_transpose_matmul",
            f"rows {x.shape[0]} % '{axis}' size {k} != 0",
        )
        return hdot(x.T, yy, precision, tier=tier)
    if _pick_tiles(x.shape[1], k, tiles, tier=tier) == 0:
        _log_fallback(
            "maybe_tiled_transpose_matmul",
            f"feature dim {x.shape[1]} has no tiling over '{axis}' size {k}"
            + (f" with tiles={tiles}" if tiles else ""),
        )
        return hdot(x.T, yy, precision, tier=tier)
    return tiled_transpose_matmul(
        x, yy, mesh=mesh, axis=axis, tiles=tiles, precision=precision,
        tier=tier,
    )


def tiled_psum_dot(
    a: jax.Array,
    b: jax.Array,
    axis: str,
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
    tiers: Optional[Tuple[int, int]] = None,
    outer_tiles: Optional[int] = None,
    tier: str = "f32",
) -> jax.Array:
    """``psum(a @ b)`` over ``axis`` for use INSIDE a ``shard_map`` body,
    tiled so each tile's reduce-scatter overlaps the next tile's matmul
    (the TSQR tree's ``Qᵀb`` reduction). ``a``: (m, p) per-shard partial
    factor, ``b``: (p, c); returns the replicated-by-construction (m, c)
    sum. Falls back to the monolithic ``psum`` when m cannot be tiled.

    ``tiers=(outer, inner)`` (from :func:`mesh_tiers`; must factor the axis
    size) splits every tile's reduction in two: an inner within-slice
    ``psum_scatter`` over ICI, then a cross-slice exchange that ships only
    the slice partials — 1/inner of the bytes — over DCN. The DCN exchanges
    are batched ``outer_tiles``-wise (default: one per slice, i.e. each DCN
    exchange hides behind ~T/outer inner tiles' MXU work; the second field
    of ``KEYSTONE_OVERLAP_TILES=T,To`` overrides): per-tier tile sizes, so
    the slow tier always has more compute to hide behind.

    ``tier="bf16"`` (the storage dtype tier, caller-resolved static) casts
    ``a``/``b`` to bfloat16 ONCE before tiling — each per-tile ``hdot``
    then reads bf16 operands and accumulates f32, so the reductions below
    always carry f32 partial products."""
    k = jax.lax.axis_size(axis)
    m = a.shape[0]
    T = tiles or _pick_tiles(m, k, tier=tier)
    if tier == "bf16":
        # one cast for all tiles (hdot's own astype is then a no-op); the
        # f32 path touches nothing — astype is identity on f32 operands
        a = a.astype(jnp.bfloat16)
        b = b.astype(jnp.bfloat16)
    if k <= 1 or T == 0 or m % (T * k):
        # per-trace monolithic-psum decision (no log: the eager wrappers
        # already log their own shape fallbacks; the counter keeps the
        # in-shard_map sites — e.g. the TSQR Qᵀb reduction — visible)
        _count(
            "fallback", site="tiled_psum_dot",
            reason="trivial_axis" if k <= 1 else "no_tiling",
        )
        return jax.lax.psum(hdot(a, b, precision, tier=tier), axis)
    # a tier map probed from a different axis (or hand-tuned wrong) must
    # not silently run single-tier — _resolve_tiers logs the degradation
    outer, inner = _resolve_tiers(tiers, k, "tiled_psum_dot")
    tb = m // T
    partials = [
        hdot(a[t * tb : (t + 1) * tb], b, precision, tier=tier)
        for t in range(T)
    ]
    from keystone_tpu.telemetry import get_registry as _reg

    _count(
        "engaged", site="tiled_psum_dot",
        schedule="two_tier" if outer > 1 else "single_tier",
    )
    _reg().observe("overlap.tiles", T, site="tiled_psum_dot")
    return _reduce_tiled_partials(partials, axis, k, outer, inner, outer_tiles)


def tiled_psum(
    x: jax.Array,
    axis: str,
    tiles: Optional[int] = None,
    tiers: Optional[Tuple[int, int]] = None,
    outer_tiles: Optional[int] = None,
) -> jax.Array:
    """``psum(x)`` over ``axis`` for use INSIDE a ``shard_map`` body, with
    x's rows chunked into tiles so each tile's reduce-scatter can overlap
    neighboring compute — the reduction half of :func:`tiled_psum_dot`, for
    callers whose per-shard partials are not themselves a matmul (the
    CountSketch segment-sum partials, ``linalg/sketch.py``). ``x``: (m, c)
    per-shard partial; returns the replicated-by-construction sum. Two-tier
    aware exactly like :func:`tiled_psum_dot`; falls back to the monolithic
    ``psum`` when m cannot be tiled."""
    k = jax.lax.axis_size(axis)
    m = x.shape[0]
    T = tiles or _pick_tiles(m, k)
    if k <= 1 or T == 0 or m % (T * k):
        _count(
            "fallback", site="tiled_psum",
            reason="trivial_axis" if k <= 1 else "no_tiling",
        )
        return jax.lax.psum(x, axis)
    outer, inner = _resolve_tiers(tiers, k, "tiled_psum")
    tb = m // T
    partials = [x[t * tb : (t + 1) * tb] for t in range(T)]
    from keystone_tpu.telemetry import get_registry as _reg

    _count(
        "engaged", site="tiled_psum",
        schedule="two_tier" if outer > 1 else "single_tier",
    )
    _reg().observe("overlap.tiles", T, site="tiled_psum")
    return _reduce_tiled_partials(partials, axis, k, outer, inner, outer_tiles)


def _resolve_tiers(
    tiers: Optional[Tuple[int, int]], k: int, site: str
) -> Tuple[int, int]:
    """Validate a (outer, inner) tier map against the axis size; anything
    that does not factor ``k`` degrades to single-tier WITH a log — the
    operator who set a tier map must not silently lose the DCN schedule."""
    outer, inner = tiers or (1, k)
    if outer > 1 and outer * inner != k:
        _log_fallback(
            site, f"tiers {tiers} do not factor the axis size {k}",
        )
        outer, inner = 1, k
    if outer <= 1:
        outer, inner = 1, k
    return outer, inner


def _reduce_tiled_partials(
    partials, axis: str, k: int, outer: int, inner: int,
    outer_tiles: Optional[int] = None,
) -> jax.Array:
    """Shared reduction tail of the tiled schedules: per-tile
    ``psum_scatter`` (single- or two-tier ICI/DCN) + ONE trailing
    ``all_gather`` + the device-order unscramble. ``partials``: T equal
    (tb, c) row-tiles of the (m, c) array to sum over ``axis``."""
    from keystone_tpu.telemetry import get_registry as _reg

    T = len(partials)
    tb, c = partials[0].shape
    pb = tb // k
    m = T * tb
    _reg().inc("overlap.tier_schedule", schedule=f"{outer}x{inner}")
    if outer == 1:
        _count("reduce_scatter_rounds", T, tier="single")
        pieces = [
            jax.lax.psum_scatter(p, axis, scatter_dimension=0, tiled=True)
            for p in partials
        ]
        full = jax.lax.all_gather(jnp.concatenate(pieces, 0), axis)
        return full.reshape(k, T, pb, c).transpose(1, 0, 2, 3).reshape(m, c)
    inner_groups, outer_groups = _tier_groups(outer, inner)
    # inner tier (ICI): one within-slice reduce-scatter per tile — device
    # (s, j) ends with rows [j·pb·outer, (j+1)·pb·outer) of the tile,
    # summed over its slice s.
    inner_pieces = [
        jax.lax.psum_scatter(
            p, axis, scatter_dimension=0, tiled=True,
            axis_index_groups=inner_groups,
        )
        for p in partials
    ]
    # outer tier (DCN): cross-slice exchanges of the slice partials,
    # batched r inner tiles per exchange (per-tier tile sizes).
    To = outer_tiles or _env_tiles()[1] or min(T, outer)
    r = -(-T // max(To, 1))
    _count("reduce_scatter_rounds", T, tier="inner")
    _count("reduce_scatter_rounds", -(-T // r), tier="outer")
    pieces = []
    for g0 in range(0, T, r):
        stack = jnp.stack(inner_pieces[g0 : g0 + r])  # (r', pb·outer, c)
        red = jax.lax.psum_scatter(
            stack, axis, scatter_dimension=1, tiled=True,
            axis_index_groups=outer_groups,
        )  # (r', pb, c): device (s, j) holds sub-chunk s of its chunk j
        pieces.append(red.reshape(-1, c))
    full = jax.lax.all_gather(jnp.concatenate(pieces, 0), axis)
    # device i = s·inner + j holds, per tile, chunk q = j·outer + s — the
    # reorder below walks (tile, j, s) so chunks land in ascending order.
    return (
        full.reshape(outer, inner, T, pb, c)
        .transpose(2, 1, 0, 3, 4)
        .reshape(m, c)
    )


def bidirectional_ring_gram(
    x: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "model",
    precision: str = "highest",
    tier: str = "f32",
) -> jax.Array:
    """``XᵀX`` with the feature axis sharded over ``axis`` — the
    bidirectional schedule of ``ring.ring_gram``.

    Two copies of the resident column block circulate the ring in opposite
    directions via PAIRED ``ppermute``s: after round t, the forward copy on
    device j holds block j-t and the backward copy block j+t, so each round
    fills TWO gram tiles and the ring completes in ⌈(k-1)/2⌉ rounds instead
    of k-1 — both ICI links carry traffic every step and each block travels
    at most half the ring (half the per-link wire time of the unidirectional
    rotation). Every tile is the same ``hdot`` on the same operands as the
    unidirectional schedule, so the output is bit-identical to
    ``ring_gram(..., bidirectional=False)`` — at the default f32 tier;
    ``tier="bf16"`` trades that bit-identity for bf16 resident blocks
    (half the ring's wire bytes) with f32 tile accumulation.

    The rounds are unrolled (k is static and small): the compiled HLO shows
    the paired collective-permutes per round — the structure the comm-pattern
    tests pin — and gives the scheduler independent permute/matmul chains to
    overlap. Odd k needs no special case; even k has one unpaired middle
    block (distance k/2, reachable equally from either direction) folded via
    a single final forward hop.
    """
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    k = mesh.shape[axis]
    d = x.shape[1]
    if d % k:
        raise ValueError(
            f"feature dim {d} must be divisible by the '{axis}' axis size {k}"
        )
    db = d // k
    _count("engaged", site="bidirectional_ring_gram")
    _count(
        "ppermute_rounds",
        2 * bidirectional_rounds(k) + (1 if k % 2 == 0 and k > 1 else 0),
        site="bidirectional_ring_gram",
    )

    def local(xj):
        # bf16 tier: the RESIDENT block is cast once; ring hops then carry
        # bf16 payloads (half the per-link wire bytes — the storage tier's
        # second win on this schedule) while every tile still accumulates
        # f32 via hdot's preferred_element_type.
        acc_dtype = jnp.float32 if tier == "bf16" else xj.dtype
        xj = xj.astype(jnp.bfloat16) if tier == "bf16" else xj

        def fold(src, visiting, out):
            # (db, db): X_srcᵀ X_j, f32 accumulator under the bf16 tier
            tile = hdot(visiting.T, xj, precision, tier=tier)
            return jax.lax.dynamic_update_slice(out, tile, (src * db, 0))

        out = jax.lax.pcast(jnp.zeros((d, db), acc_dtype), axis, to="varying")
        return _ring_rotate_fold(xj, axis, k, fold, out)

    spec = P(None, axis)
    return jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)(x)


def _ring_rotate_fold(x0, axis: str, k: int, fold, out):
    """The one bidirectional rotation schedule, shared by every block-ring
    consumer (feature-sharded gram above, the model-axis gram below): fold
    the resident block, then ⌈(k-1)/2⌉ paired fwd/bwd ``ppermute`` rounds
    folding both arrivals, then the even-k unpaired middle hop.
    ``fold(src, visiting, out)`` folds the block that originated on device
    ``src``. Keeping the schedule in one place means a fix to the rotation
    (and the permute counts the comm-pattern tests pin) cannot silently
    apply to one consumer and not the other."""
    j = jax.lax.axis_index(axis)
    fwd_perm, bwd_perm = paired_ring_perms(k)  # j receives from j∓1
    out = fold(j, x0, out)  # own block, no hop
    fwd = bwd = x0
    for t in range(1, bidirectional_rounds(k) + 1):
        fwd = jax.lax.ppermute(fwd, axis, fwd_perm)
        bwd = jax.lax.ppermute(bwd, axis, bwd_perm)
        out = fold((j - t) % k, fwd, out)
        out = fold((j + t) % k, bwd, out)
    if k % 2 == 0 and k > 1:
        # unpaired middle block at distance k/2: one more forward hop
        fwd = jax.lax.ppermute(fwd, axis, fwd_perm)
        out = fold((j - k // 2) % k, fwd, out)
    return out


def _tier_ring_perm_tables(outer: int, inner: int):
    """``ppermute`` tables for the two-stage tiered fold (flat device index
    i = slice·inner + lane): within-slice rings — each slice its own cycle
    over its ``inner`` devices (ICI hops only) — and cross-slice rings —
    each lane its own cycle over the ``outer`` slices (the only DCN
    hops)."""
    win_fwd = [(s * inner + j, s * inner + (j + 1) % inner)
               for s in range(outer) for j in range(inner)]
    win_bwd = [(s * inner + j, s * inner + (j - 1) % inner)
               for s in range(outer) for j in range(inner)]
    cross_fwd = [(s * inner + j, ((s + 1) % outer) * inner + j)
                 for s in range(outer) for j in range(inner)]
    cross_bwd = [(s * inner + j, ((s - 1) % outer) * inner + j)
                 for s in range(outer) for j in range(inner)]
    return win_fwd, win_bwd, cross_fwd, cross_bwd


def ring_tsqr_fold(
    Ri: jax.Array,
    Zi: Optional[jax.Array],
    axis: str,
    precision: Optional[str] = None,
    tiers: Optional[Tuple[int, int]] = None,
    tier: str = "f32",
):
    """The overlapped TSQR R-tree, for use INSIDE a ``shard_map`` body.

    ``Ri``: this shard's R factor from its local QR; ``Zi``: this shard's
    rotated rhs contribution ``Qᵢᵀbᵢ`` (None when only R is wanted, e.g.
    ``tsqr_r``). Instead of one bulk ``all_gather`` of the R_i stack
    followed by one monolithic second-level QR, the original (R_i, Z_i)
    pairs circulate the ring in BOTH directions via paired ``ppermute``s
    (the :func:`bidirectional_ring_gram` machinery) and every arrival is
    folded into a running panel factorization:

        Q, R_acc ← qr([R_acc; R_fwd; R_bwd]),  Z_acc ← Qᵀ[Z_acc; Z_fwd; Z_bwd]

    so round t's permute is in flight while round t-1's panel QR runs on
    the compute units, and the ``Qᵀb`` reduction rides through the same
    fold — no separate psum, no bulk collective at all. ⌈(k-1)/2⌉ paired
    rounds (+ one forward hop for even k); works for ANY shard count and
    any d (no tiling divisibility requirement).

    ``tiers=(outer, inner)`` (from :func:`mesh_tiers`) engages the
    tier-aware fold order on multi-slice meshes: the within-slice factors
    fold FIRST over each slice's own bidirectional ICI ring, and only the
    ``outer`` already-folded per-slice results circulate across slices —
    every cross-slice (DCN) payload is one (d, d) R (+ rhs) per slice
    instead of every round's raw factor, and the slow tier's hop count
    drops from ~k-1 ring steps to the outer-1 slice-result hops. Same
    folded set either way, so the (R, Z) contract is unchanged.

    Returns (R, Z): replicated by construction up to fold order — every
    device folds the same set of factors, so RᵀR (and the least-squares
    solution R⁻¹Z) agree to rounding; row signs of R may differ between
    devices, but each device's (R, Z) pair is internally consistent, which
    is all the triangular solve consumes."""
    k = jax.lax.axis_size(axis)
    if k <= 1:
        _count("fallback", site="ring_tsqr_fold", reason="trivial_axis")
        return Ri, Zi
    outer, inner = _resolve_tiers(tiers, k, "ring_tsqr_fold")
    _count("engaged", site="ring_tsqr_fold")

    def fold(R_acc, Z_acc, Rs, Zs):
        # panel QRs stay f32 at every tier (the rung's O(κ) stability);
        # the tier applies only to the Qᵀ[Z…] product's operand storage
        stack = jnp.concatenate([R_acc] + Rs, axis=0)
        if Z_acc is None:
            return jnp.linalg.qr(stack, mode="r"), None
        Q, R = jnp.linalg.qr(stack, mode="reduced")
        return R, hdot(
            Q.T, jnp.concatenate([Z_acc] + Zs, axis=0), precision, tier=tier
        )

    def circulate(R_acc, Z_acc, R0, Z0, fwd_perm, bwd_perm, ksub):
        """One bidirectional fold stage over a ``ksub``-cycle of the perm
        tables: circulate (R0, Z0) both ways, folding every arrival into
        the accumulators — the single-ring schedule, reused per tier."""
        fR = bR = R0
        fZ = bZ = Z0
        for _ in range(bidirectional_rounds(ksub)):
            if Z0 is None:
                fR = jax.lax.ppermute(fR, axis, fwd_perm)
                bR = jax.lax.ppermute(bR, axis, bwd_perm)
            else:
                fR, fZ = jax.lax.ppermute((fR, fZ), axis, fwd_perm)
                bR, bZ = jax.lax.ppermute((bR, bZ), axis, bwd_perm)
            R_acc, Z_acc = fold(R_acc, Z_acc, [fR, bR], [fZ, bZ])
        if ksub % 2 == 0 and ksub > 1:
            # unpaired middle factor at distance ksub/2: one forward hop
            if Z0 is None:
                fR = jax.lax.ppermute(fR, axis, fwd_perm)
            else:
                fR, fZ = jax.lax.ppermute((fR, fZ), axis, fwd_perm)
            R_acc, Z_acc = fold(R_acc, Z_acc, [fR], [fZ])
        return R_acc, Z_acc

    def stage_rounds(ksub):
        return 2 * bidirectional_rounds(ksub) + (
            1 if ksub % 2 == 0 and ksub > 1 else 0
        )

    if outer <= 1:
        _count(
            "ppermute_rounds", stage_rounds(k), site="ring_tsqr_fold",
        )
        fwd_perm, bwd_perm = paired_ring_perms(k)
        return circulate(Ri, Zi, Ri, Zi, fwd_perm, bwd_perm, k)
    # ONE engaged count per fold (fired above, untagged — the series the
    # telemetry tests read); the two-tier schedule is recorded on the
    # tier_schedule series, the tiled paths' convention
    from keystone_tpu.telemetry import get_registry as _reg

    _reg().inc("overlap.tier_schedule", schedule=f"{outer}x{inner}")
    _count(
        "ppermute_rounds", stage_rounds(inner), site="ring_tsqr_fold",
        tier="inner",
    )
    _count(
        "ppermute_rounds", stage_rounds(outer), site="ring_tsqr_fold",
        tier="outer",
    )
    win_fwd, win_bwd, cross_fwd, cross_bwd = _tier_ring_perm_tables(
        outer, inner
    )
    # stage 1 (ICI): fold this slice's factors over its own ring — after
    # this every device holds its slice's (R_s, Z_s)
    R_acc, Z_acc = circulate(Ri, Zi, Ri, Zi, win_fwd, win_bwd, inner)
    # stage 2 (DCN): circulate ONLY the per-slice results across slices —
    # each lane runs an independent outer-ring of the slice R factors
    return circulate(R_acc, Z_acc, R_acc, Z_acc, cross_fwd, cross_bwd, outer)


def model_tiled_transpose_matmul(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    data_axis: str = "data",
    model_axis: str = "model",
    tiles: Optional[int] = None,
    precision: Optional[str] = None,
    tier: str = "f32",
) -> jax.Array:
    """Replicated ``XᵀY`` (``y=None`` → the gram ``XᵀX``) for a
    column-sharded ``x``: (n, dx) with ``P(data_axis, model_axis)`` — the
    256k-dim BCD regime where one chip cannot hold a block's columns.

    The gram composes BOTH overlap schedules: the resident column block of
    every model rank rotates the model-axis ring bidirectionally (paired
    ``ppermute``s, the :func:`bidirectional_ring_gram` schedule) while each
    visiting×resident tile's row reduction runs as the tiled data-axis
    reduce-scatter (:func:`tiled_psum_dot`, two-tier aware) — so the model
    hop of rotation t overlaps the data-axis reduction of rotation t-1,
    which itself overlaps the next tile's matmul. The cross term (``y``:
    (n, c) sharded ``P(data_axis, None)``) needs no rotation: each rank
    reduces its resident columns against y and one model-axis ``all_gather``
    assembles the (dx, c) result.

    Raises ``ValueError`` on shapes the two-axis tiling cannot divide —
    callers (``linalg/bcd.py``) gate on :func:`model_overlap_spec` at trace
    time instead of calling blindly."""
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    kd = mesh.shape[data_axis]
    km = mesh.shape[model_axis]
    n, dx = x.shape
    if n % kd:
        raise ValueError(
            f"row count {n} must be divisible by the '{data_axis}' axis "
            f"size {kd}"
        )
    if dx % km:
        raise ValueError(
            f"feature dim {dx} must be divisible by the '{model_axis}' "
            f"axis size {km}"
        )
    dl = dx // km
    tiers = mesh_tiers(mesh, data_axis)
    _count(
        "engaged", site="model_tiled_transpose_matmul",
        kind="cross" if y is not None else "gram",
        schedule="two_tier" if tiers[0] > 1 else "single_tier",
    )

    if y is not None:
        if y.shape[0] != n:
            raise ValueError(
                f"row mismatch: x has {n} rows, y has {y.shape[0]}"
            )
        c = y.shape[1]

        def local_cross(xij, yi):
            cj = tiled_psum_dot(
                xij.T, yi, data_axis, tiles=tiles, precision=precision,
                tiers=tiers, tier=tier,
            )  # (dl, c), replicated over data by construction
            full = jax.lax.all_gather(cj, model_axis)  # (km, dl, c)
            return full.reshape(dx, c)

        return jax.shard_map(
            local_cross,
            mesh=mesh,
            in_specs=(P(data_axis, model_axis), P(data_axis, None)),
            out_specs=P(),
            check_vma=False,
        )(x, y)

    def local_gram(xij):
        # bf16 tier: cast the resident block once — model-axis ring hops
        # carry bf16 payloads; every tile's data-axis reduction still rides
        # the f32 accumulator (tiled_psum_dot).
        acc_dtype = jnp.float32 if tier == "bf16" else xij.dtype
        xij = xij.astype(jnp.bfloat16) if tier == "bf16" else xij

        def fold(src, visiting, out):
            # (dl, dl) tile X_srcᵀ X_j, globally row-reduced via the tiled
            # data-axis reduce-scatter (two-tier aware)
            tile = tiled_psum_dot(
                visiting.T, xij, data_axis, tiles=tiles,
                precision=precision, tiers=tiers, tier=tier,
            )
            return jax.lax.dynamic_update_slice(out, tile, (src * dl, 0))

        out = jax.lax.pcast(
            jnp.zeros((dx, dl), acc_dtype), model_axis, to="varying"
        )
        out = _ring_rotate_fold(xij, model_axis, km, fold, out)
        # out: (dx, dl) column block, replicated over data; assemble the
        # replicated (dx, dx) gram with one model-axis all_gather
        full = jax.lax.all_gather(out, model_axis)  # (km, dx, dl)
        return full.transpose(1, 0, 2).reshape(dx, dx)

    return jax.shard_map(
        local_gram,
        mesh=mesh,
        in_specs=P(data_axis, model_axis),
        out_specs=P(),
        check_vma=False,
    )(x)


def model_overlap_spec(
    A,
    omesh: Optional[Mesh],
    block_size: int,
    data_axis: str = "data",
    model_axis: str = "model",
) -> bool:
    """Trace-time gate for the column-sharded overlap path: True when the
    overlap mesh has a non-trivial model axis, ``A`` is concretely sharded
    ``P(data_axis, model_axis)``, and the per-block shapes divide both axes.
    A column-sharded ``A`` that narrowly misses (e.g. block_size not
    divisible by the model axis) logs the fallback once — the regime the
    knob was set for would otherwise silently reshard every block."""
    if omesh is None or omesh.shape.get(model_axis, 1) <= 1:
        return False
    sh = getattr(A, "sharding", None)
    if not (
        isinstance(sh, NamedSharding)
        and getattr(A, "ndim", 0) == 2
        and len(sh.spec) >= 2
        and sh.spec[1] == model_axis
    ):
        return False
    km = omesh.shape[model_axis]
    kd = omesh.shape[data_axis]
    if A.shape[0] % kd or block_size % km:
        _log_fallback(
            "model_overlap",
            f"column-sharded A {A.shape} with block {block_size} does not "
            f"divide mesh ({data_axis}={kd}, {model_axis}={km})",
        )
        return False
    return True
