from keystone_tpu.parallel.mesh import (
    make_mesh,
    get_mesh,
    use_mesh,
    data_axis_size,
    shard_rows,
    shard_cols,
    replicate,
    distribute,
)
from keystone_tpu.parallel.overlap import (
    bidirectional_ring_gram,
    maybe_tiled_transpose_matmul,
    overlap_enabled,
    overlap_mesh,
    tiled_psum_dot,
    tiled_transpose_matmul,
    use_overlap,
)
from keystone_tpu.parallel.ring import (
    ring_attention,
    ring_gram,
    ulysses_attention,
)
