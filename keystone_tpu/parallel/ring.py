"""Ring / all-to-all sequence-context parallelism over ICI.

The reference has no attention (its LM is count-based — SURVEY.md §5); its
long-dimension analog is feature-axis blocking (``VectorSplitter`` + block
solvers). This module makes the TPU-native generalization first-class, per
SURVEY.md §5's design note ("rotating feature blocks around the ring is the
natural ICI pattern when a block exceeds per-chip HBM"):

- :func:`ring_gram` — XᵀX with the *feature* axis sharded: each device holds a
  column block; blocks rotate around the ring via ``lax.ppermute`` so every
  (i, j) gram tile is computed without ever gathering full X on one chip.
  This is the beyond-HBM regime of the reference's 256k-dim Fisher-vector
  features (``ImageNetSiftLcsFV.scala:188``).

- :func:`ring_attention` — blockwise-softmax attention with the *sequence*
  axis sharded: K/V blocks rotate around the ring while each device keeps its
  Q block and a running (max, denominator, numerator) online-softmax state —
  ring attention (Liu et al.; PAPERS.md). Peak memory per chip is O(S·S/k),
  ICI traffic fully overlappable with the per-step matmuls.

- :func:`ulysses_attention` — the all-to-all alternative (DeepSpeed-Ulysses):
  reshard sequence-sharded Q/K/V to head-sharded via ``lax.all_to_all``,
  run exact local attention over the full sequence per head group, reshard
  back. Cheaper ICI volume than the ring when heads ≥ devices.

All three are ``shard_map`` programs over one mesh axis and compose with the
``data``/``model`` axes used by the solvers (``parallel/mesh.py``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from keystone_tpu.linalg.solvers import hdot as _hdot


def hdot(a, b):
    # Attention/gram matmuls here keep 6-pass f32 accuracy regardless of the
    # solver-precision knob (which is scoped to least-squares solvers).
    return _hdot(a, b, "highest")


def _ring_perm(axis_name: str):
    n = jax.lax.axis_size(axis_name)
    return [(i, (i + 1) % n) for i in range(n)]


def paired_ring_perms(k: int):
    """(fwd, bwd) ``ppermute`` tables for the bidirectional ring schedules:
    fwd rotates so device j receives from j-1, bwd so j receives from j+1.
    Shared by every bidirectional consumer (``overlap.bidirectional_ring_gram``,
    the overlapped TSQR R-tree, the model-axis block rotation) so the paired
    structure the comm-pattern tests pin is built in exactly one place."""
    fwd = [(i, (i + 1) % k) for i in range(k)]
    bwd = [(i, (i - 1) % k) for i in range(k)]
    return fwd, bwd


def bidirectional_rounds(k: int) -> int:
    """Paired rounds of the bidirectional ring: ⌈(k-1)/2⌉ with one extra
    unpaired forward hop when k is even (the distance-k/2 middle block)."""
    return (k - 1) // 2


def ring_gram(
    x: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "model",
    bidirectional: Optional[bool] = None,
    tier: Optional[str] = None,
) -> jax.Array:
    """XᵀX for ``x`` (n, d) with the feature axis sharded over ``axis``.

    Returns the gram column-sharded the same way: device j ends with the
    (d, d/k) tile ``Xᵀ X_j``. One column block circulates the ring; at step t
    each device multiplies the visiting block's transpose against its own,
    filling one (d/k, d/k) tile per step — k steps, each overlapping a
    ppermute with a matmul.

    ``bidirectional`` rotates blocks in BOTH ring directions via paired
    ppermutes — ⌈(k-1)/2⌉ rounds instead of k-1, both ICI links busy, bit-
    identical tiles (``parallel/overlap.py::bidirectional_ring_gram``).
    ``None`` resolves the overlap knob (``KEYSTONE_OVERLAP`` /
    ``use_overlap``), so existing call sites pick up the pipelined schedule
    when the knob is on.

    ``tier`` (None = the ``KEYSTONE_PRECISION_TIER`` knob) engages
    bf16-stored resident blocks on the bidirectional schedule — ring hops
    then carry bf16 payloads (half the per-link wire bytes) while every
    tile accumulates f32. The unidirectional fallback always runs f32 (it
    exists as the exact prior program, like the overlap layer's monolithic
    twins), so the f32 tier remains bit-identical either way.
    """
    from keystone_tpu.linalg.solvers import resolve_precision_tier
    from keystone_tpu.parallel.mesh import get_mesh
    from keystone_tpu.parallel.overlap import bidirectional_ring_gram, overlap_enabled

    mesh = mesh or get_mesh()
    if overlap_enabled(bidirectional):
        return bidirectional_ring_gram(
            x, mesh, axis=axis, tier=resolve_precision_tier(tier)
        )
    k = mesh.shape[axis]
    d = x.shape[1]
    if d % k:
        raise ValueError(
            f"feature dim {d} must be divisible by the '{axis}' axis size {k}"
        )
    db = d // k

    def local(xj):
        # xj: (n, db) — this device's resident column block.
        j = jax.lax.axis_index(axis)
        perm = _ring_perm(axis)

        def fold(t, visiting, out):
            # The block visiting at step t started on device (j - t) mod k.
            src = (j - t) % k
            tile = hdot(visiting.T, xj)  # (db, db): X_srcᵀ X_j
            return jax.lax.dynamic_update_slice(out, tile, (src * db, 0))

        def step(t, carry):
            visiting, out = carry
            out = fold(t, visiting, out)
            return jax.lax.ppermute(visiting, axis, perm), out

        # pcast: the zeros are logically replicated but the loop carry becomes
        # device-varying after the first update, so type them varying up front.
        out = jax.lax.pcast(jnp.zeros((d, db), xj.dtype), axis, to="varying")
        # k-1 rotations; the last visiting block is consumed without a
        # (wasted) final ppermute.
        visiting, out = jax.lax.fori_loop(0, k - 1, step, (xj, out))
        return fold(k - 1, visiting, out)

    spec = P(None, axis)
    return jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)(x)


def _online_softmax_step(q, kb, vb, state, bias):
    """One block of numerically-stable streaming softmax attention.

    state = (m, l, acc): running rowwise max, denominator, numerator.
    """
    m, l, acc = state
    s = hdot(q, kb.swapaxes(-1, -2)) * (q.shape[-1] ** -0.5)
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    scale = jnp.exp(m - m_new)
    l = l * scale + p.sum(axis=-1)
    acc = acc * scale[..., None] + hdot(p, vb)
    return m_new, l, acc


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    causal: bool = False,
) -> jax.Array:
    """Exact attention with the sequence axis sharded over ``axis``.

    ``q``/``k``/``v``: (batch, seq, heads, head_dim), seq sharded. K/V blocks
    rotate the ring; each device folds every visiting block into its online
    softmax state, so the full (S, S) score matrix never exists. ``causal``
    masks by *global* position, reconstructed from the ring step.
    """
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    nk = mesh.shape[axis]
    if q.shape[1] % nk:
        raise ValueError(
            f"sequence length {q.shape[1]} must be divisible by the "
            f"'{axis}' axis size {nk}"
        )
    sb = q.shape[1] // nk
    neg = jnp.finfo(jnp.float32).min

    def local(qj, kj, vj):
        j = jax.lax.axis_index(axis)
        perm = _ring_perm(axis)
        # (B, Sb, H, D) -> (B, H, Sb, D) for batched matmuls on the MXU.
        qj, kj, vj = (t.swapaxes(1, 2).astype(jnp.float32) for t in (qj, kj, vj))
        B, H, S, D = qj.shape
        q_pos = j * sb + jnp.arange(sb)

        def fold(t, kb, vb, state):
            src = (j - t) % nk
            if causal:
                k_pos = src * sb + jnp.arange(sb)
                bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, neg)
            else:
                bias = None
            return _online_softmax_step(qj, kb, vb, state, bias)

        def step(t, carry):
            (kb, vb), state = carry
            state = fold(t, kb, vb, state)
            return jax.lax.ppermute((kb, vb), axis, perm), state

        state = jax.lax.pcast(
            (
                jnp.full((B, H, S), neg),
                jnp.zeros((B, H, S)),
                jnp.zeros((B, H, S, D)),
            ),
            axis,
            to="varying",
        )
        # nk-1 rotations; the final visiting block needs no onward ppermute.
        (kb, vb), state = jax.lax.fori_loop(0, nk - 1, step, ((kj, vj), state))
        m, l, acc = fold(nk - 1, kb, vb, state)
        out = acc / l[..., None]
        return out.swapaxes(1, 2)

    spec = P(None, axis, None, None)
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    causal: bool = False,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Input sequence-sharded (B, S/k, H, D); one ``all_to_all`` reshards to
    head-sharded (B, S, H/k, D), each device runs exact full-sequence
    attention on its head group, a second ``all_to_all`` reshards back.
    Requires heads divisible by the axis size.
    """
    from keystone_tpu.parallel.mesh import get_mesh

    mesh = mesh or get_mesh()
    nk = mesh.shape[axis]
    if q.shape[2] % nk:
        raise ValueError(
            f"heads {q.shape[2]} must be divisible by the '{axis}' axis size {nk}"
        )
    neg = jnp.finfo(jnp.float32).min

    def local(qj, kj, vj):
        # (B, Sb, H, D) -> (B, S, Hb, D): gather seq, scatter heads.
        a2a = functools.partial(
            jax.lax.all_to_all, axis_name=axis, split_axis=2, concat_axis=1, tiled=True
        )
        qf, kf, vf = a2a(qj), a2a(kj), a2a(vj)
        qf, kf, vf = (t.swapaxes(1, 2).astype(jnp.float32) for t in (qf, kf, vf))
        s = hdot(qf, kf.swapaxes(-1, -2)) * (qf.shape[-1] ** -0.5)
        if causal:
            S = s.shape[-1]
            s = jnp.where(
                jnp.arange(S)[:, None] >= jnp.arange(S)[None, :], s, neg
            )
        out = hdot(jax.nn.softmax(s, axis=-1), vf).swapaxes(1, 2)
        # (B, S, Hb, D) -> (B, Sb, H, D): gather heads, scatter seq.
        return jax.lax.all_to_all(
            out, axis_name=axis, split_axis=1, concat_axis=2, tiled=True
        )

    spec = P(None, axis, None, None)
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def attention_reference(q, k, v, causal: bool = False) -> jax.Array:
    """Unsharded exact attention (the correctness oracle for the tests)."""
    q, k, v = (t.swapaxes(1, 2).astype(jnp.float32) for t in (q, k, v))
    s = hdot(q, k.swapaxes(-1, -2)) * (q.shape[-1] ** -0.5)
    if causal:
        S = s.shape[-1]
        s = jnp.where(
            jnp.arange(S)[:, None] >= jnp.arange(S)[None, :],
            s,
            jnp.finfo(jnp.float32).min,
        )
    return hdot(jax.nn.softmax(s, axis=-1), v).swapaxes(1, 2)
