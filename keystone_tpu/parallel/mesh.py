"""Device mesh + sharding helpers: the distributed substrate.

The reference's distributed substrate is the Spark driver/executor runtime
(SURVEY.md §2.13): broadcast, treeAggregate/treeReduce, shuffle, zip, collect.
The TPU-native equivalents, used throughout this framework:

- RDD row partitioning      -> ``NamedSharding(mesh, P('data'))`` on the item axis
- broadcast of a model      -> replicated sharding (``P()``)
- treeReduce of gram mats   -> a sharded matmul whose output is replicated:
  XLA inserts the all-reduce over ICI (``X.T @ X`` with ``X`` row-sharded)
- mapPartitions             -> ``jax.shard_map`` when per-shard control is needed
- zip of co-partitioned RDDs-> elementwise op on identically-sharded arrays

Axes convention: ``data`` shards the item/row axis (data parallelism),
``model`` shards the feature/column axis (the analog of the reference's
``VectorSplitter`` feature-block model parallelism,
``nodes/util/VectorSplitter.scala:10-34``).
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.core.dataset import Dataset, pad_rows

_MESH_STACK: list[Mesh] = []


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a 2D ``(data, model)`` mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        data = len(devices) // model
    if data * model != len(devices):
        devices = devices[: data * model]
    # lint: disable=R1 (np.array over device *handles* — host-side mesh
    # construction at trace/setup time, not an array transfer)
    arr = np.array(devices).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def get_mesh() -> Mesh:
    """Current mesh: innermost ``use_mesh`` context, else all devices as 1×N data mesh."""
    if _MESH_STACK:
        return _MESH_STACK[-1]
    return make_mesh()


def current_mesh() -> Optional[Mesh]:
    """Innermost ``use_mesh`` mesh, or None when no mesh context is active
    (unlike :func:`get_mesh`, never constructs one)."""
    return _MESH_STACK[-1] if _MESH_STACK else None


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    _MESH_STACK.append(mesh)
    try:
        yield mesh
    finally:
        _MESH_STACK.pop()


def data_axis_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape["data"]


def _spec_for_rows(ndim: int) -> P:
    return P(*(("data",) + (None,) * (ndim - 1)))


def shard_rows(x: jax.Array, mesh: Optional[Mesh] = None) -> jax.Array:
    """Shard the leading (item) axis over the ``data`` mesh axis.

    The row count must be divisible by the data axis; use :func:`distribute`
    to pad+mask arbitrary row counts.
    """
    mesh = mesh or get_mesh()
    return jax.device_put(x, NamedSharding(mesh, _spec_for_rows(np.ndim(x))))


def shard_cols(x: jax.Array, mesh: Optional[Mesh] = None, axis: int = -1) -> jax.Array:
    """Shard a feature/column axis over the ``model`` mesh axis."""
    mesh = mesh or get_mesh()
    axis = axis % np.ndim(x)
    spec = [None] * np.ndim(x)
    spec[axis] = "model"
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def replicate(x, mesh: Optional[Mesh] = None):
    """Replicated sharding: the broadcast analog."""
    mesh = mesh or get_mesh()
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), x)


def distribute(x: jax.Array, mesh: Optional[Mesh] = None) -> Dataset:
    """Pad rows to a multiple of the data axis, shard, and return a masked
    :class:`Dataset` — the standard way host data enters the mesh."""
    mesh = mesh or get_mesh()
    padded, mask = pad_rows(x, data_axis_size(mesh))
    return Dataset(data=shard_rows(padded, mesh), mask=shard_rows(mask, mesh))
