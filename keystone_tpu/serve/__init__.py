"""Hardened serving tier (``keystone_tpu/serve/gateway.py``): the
admission-checked prediction gateway with deadline-aware load shedding,
circuit breaking, and graceful degradation."""

from keystone_tpu.serve.gateway import (
    DEFAULT_SHAPES,
    Gateway,
    PendingResponse,
    ServeRejected,
    ServeResponse,
    serve,
)

__all__ = [
    "DEFAULT_SHAPES",
    "Gateway",
    "PendingResponse",
    "ServeRejected",
    "ServeResponse",
    "serve",
]
