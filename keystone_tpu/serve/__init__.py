"""Hardened serving tier: the admission-checked prediction gateway
(``gateway.py``) plus the fleet layer above it — multi-tenant model pools
with declared HBM envelopes (``pool.py``), the cross-process batching
front (``front.py``), and replicated gateways behind one admission
surface (``fleet.py``)."""

from keystone_tpu.serve.fleet import Fleet, FleetDown
from keystone_tpu.serve.front import BatchingFront, FrontClient, FrontError
from keystone_tpu.serve.gateway import (
    DEFAULT_SHAPES,
    Gateway,
    PendingResponse,
    ServeRejected,
    ServeResponse,
    serve,
)
from keystone_tpu.serve.pool import ModelPool, ladder_peak_bytes, pool

__all__ = [
    "BatchingFront",
    "DEFAULT_SHAPES",
    "Fleet",
    "FleetDown",
    "FrontClient",
    "FrontError",
    "Gateway",
    "ModelPool",
    "PendingResponse",
    "ServeRejected",
    "ServeResponse",
    "ladder_peak_bytes",
    "pool",
    "serve",
]
