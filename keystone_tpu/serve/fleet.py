"""Replicated gateways: N serving processes behind ONE admission surface.

The third fleet tier (pool -> front -> replicas): a :class:`Fleet` spawns
``KEYSTONE_SERVE_REPLICAS`` worker processes, each hosting a
:class:`~keystone_tpu.serve.pool.ModelPool` (built from a named
deterministic builder, ``serve/builders.py``) behind a
:class:`~keystone_tpu.serve.front.BatchingFront` unix socket.  The parent
is the admission surface:

- **Routing** is least-loaded: each live replica's outstanding-request
  count (parent-side) breaks toward the emptiest socket; drivers that want
  raw throughput take :meth:`routes` and connect directly (the router
  hands out ROUTES, it is not a proxy bottleneck).
- **Shared load-shedding state**: :meth:`stats` polls every replica's
  front (queue depth, shed totals, compile-cache size, per-tenant
  accounting) into one view; a replica whose socket errors is marked dead
  and leaves the route set.
- **No wedge under replica death** (the chaos contract): a predict whose
  replica dies mid-flight gets ONE retry on a surviving replica; with no
  survivors it returns a structured ``fleet_down`` dict.  SIGKILLing a
  replica under load (``Fleet.kill`` or a per-replica
  ``KEYSTONE_FAULTS=serve.dispatch@N:kill`` plan riding the existing
  fault sites) rebalances traffic onto the survivors.

Replica environments are scrubbed: ``XLA_FLAGS`` is dropped (the 8-device
host-platform sim is a test harness concern; a serving replica wants the
real device set) and ``JAX_PLATFORMS`` defaults to the parent's value.
Workers signal readiness by printing ``READY <socket>`` and exit when the
parent closes their stdin — so a crashed parent reaps its fleet.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from keystone_tpu.serve.front import FrontClient, FrontError
from keystone_tpu.utils.lockwitness import register_lock

__all__ = ["Fleet", "FleetDown"]


class FleetDown(RuntimeError):
    """Every replica is dead — the admission surface has nothing to route
    to (returned as a structured dict by :meth:`Fleet.predict`; raised
    only by :meth:`Fleet.require_live`)."""


class _Replica:
    def __init__(self, index: int, proc: subprocess.Popen, path: str):
        self.index = index
        self.proc = proc
        self.path = path
        self.client: Optional[FrontClient] = None
        self.dead = False
        self.outstanding = 0


class Fleet:
    """Spawn + route over N replica gateways (module docstring).

    ``builder`` names a ``serve/builders.py`` entry (or ``module:attr``);
    ``faults`` maps replica index -> a ``KEYSTONE_FAULTS`` plan armed in
    that replica only (the chaos hook).  Worker knobs (``shapes``,
    ``coalesce_ms``, ``slo_ms``, ``queue_depth``, ``hbm_mb``) are passed
    through on the worker command line."""

    def __init__(self, builder: str, replicas: Optional[int] = None, *,
                 socket_dir: Optional[str] = None,
                 shapes: Optional[str] = None,
                 coalesce_ms: Optional[float] = None,
                 slo_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 hbm_mb: Optional[float] = None,
                 faults: Optional[Dict[int, str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 ready_timeout_s: float = 120.0):
        from keystone_tpu.utils import knobs

        self.builder = builder
        n = int(replicas if replicas is not None
                else knobs.get("KEYSTONE_SERVE_REPLICAS"))
        if n < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {n}")
        self._own_dir = socket_dir is None
        self.socket_dir = socket_dir or tempfile.mkdtemp(
            prefix="keystone-fleet-"
        )
        self._worker_args: List[str] = []
        if shapes is not None:
            self._worker_args += ["--shapes", str(shapes)]
        if coalesce_ms is not None:
            self._worker_args += ["--coalesce-ms", str(coalesce_ms)]
        if slo_ms is not None:
            self._worker_args += ["--slo-ms", str(slo_ms)]
        if queue_depth is not None:
            self._worker_args += ["--queue-depth", str(queue_depth)]
        if hbm_mb is not None:
            self._worker_args += ["--hbm-mb", str(hbm_mb)]
        self._extra_env = dict(env or {})
        self._faults = dict(faults or {})
        self._lock = register_lock(threading.Lock(), "serve.fleet")
        self.replicas: List[_Replica] = [
            self._spawn(i) for i in range(n)
        ]
        self._await_ready(ready_timeout_s)

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, index: int) -> _Replica:
        path = os.path.join(self.socket_dir, f"replica-{index}.sock")
        # -c (not -m): runpy would import keystone_tpu.serve, whose
        # __init__ imports this module, and then re-execute it — a
        # double-import warning and two module objects
        cmd = [
            sys.executable, "-c",
            "import sys; from keystone_tpu.serve.fleet import _worker_main;"
            " sys.exit(_worker_main(sys.argv[1:]))",
            "--worker", "--builder", self.builder, "--socket", path,
        ] + self._worker_args
        env = dict(os.environ)
        # the 8-device host-platform sim (tests' XLA_FLAGS) would make
        # every replica trace sharded programs it doesn't want; serving
        # replicas see the real device set
        env.pop("XLA_FLAGS", None)
        env.update(self._extra_env)
        # pid+role-unique telemetry shard names: each replica exports as
        # replica-<i> unless the caller tagged the fleet itself
        env.setdefault("KEYSTONE_TELEMETRY_ROLE", f"replica-{index}")
        plan = self._faults.get(index)
        if plan is not None:
            env["KEYSTONE_FAULTS"] = plan
        proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=None, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
        )
        return _Replica(index, proc, path)

    def _await_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        for rep in self.replicas:
            while True:
                if time.monotonic() > deadline:
                    self.close()
                    raise TimeoutError(
                        f"replica {rep.index} not READY within {timeout_s}s"
                    )
                line = rep.proc.stdout.readline()
                if not line:
                    rc = rep.proc.poll()
                    self.close()
                    raise RuntimeError(
                        f"replica {rep.index} exited (rc={rc}) before READY"
                    )
                if line.startswith("READY "):
                    break
                print(f"[replica-{rep.index}] {line.rstrip()}",
                      file=sys.stderr)
            rep.client = FrontClient(rep.path)

    def kill(self, index: int) -> None:
        """SIGKILL one replica (the chaos hammer — no drain, no goodbye)."""
        rep = self.replicas[index]
        try:
            rep.proc.kill()
        except OSError:
            pass
        self._mark_dead(rep)

    def close(self) -> None:
        for rep in self.replicas:
            if rep.client is not None:
                rep.client.close()
            if rep.proc.poll() is None:
                try:
                    rep.proc.stdin.close()  # workers exit on stdin EOF
                except OSError:
                    pass
        deadline = time.monotonic() + 5.0
        for rep in self.replicas:
            while rep.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if rep.proc.poll() is None:
                try:
                    rep.proc.send_signal(signal.SIGKILL)
                except OSError:
                    pass
            try:
                rep.proc.wait(timeout=5.0)
            except Exception:
                pass
        if self._own_dir:
            import shutil

            shutil.rmtree(self.socket_dir, ignore_errors=True)

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- routing (the admission surface) -----------------------------------

    def _mark_dead(self, rep: _Replica) -> None:
        with self._lock:
            rep.dead = True
        if rep.client is not None:
            rep.client.close()
            rep.client = None

    def _live(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self.replicas
                    if not r.dead and r.client is not None]

    def live_count(self) -> int:
        return len(self._live())

    def routes(self) -> List[str]:
        """Live replica socket paths — high-volume drivers connect
        directly; the fleet hands out routes instead of proxying bytes."""
        return [r.path for r in self._live()]

    def require_live(self) -> None:
        if not self._live():
            raise FleetDown("no live replicas")

    def predict(self, x, deadline_ms: Optional[float] = None,
                model: Optional[str] = None,
                trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Route one request to the least-loaded live replica.  A socket
        failure marks the replica dead and retries ONCE on a survivor;
        with no survivors the caller gets a structured ``fleet_down`` dict
        — never an unhandled socket error, never a wedge.  ``trace_id``
        rides the front frame so the replica's spans join the caller's
        distributed trace."""
        for _attempt in range(2):
            live = self._live()
            if not live:
                break
            rep = min(live, key=lambda r: (r.outstanding, r.index))
            rep.outstanding += 1
            try:
                return rep.client.predict(
                    x, deadline_ms=deadline_ms, model=model,
                    trace_id=trace_id,
                )
            except FrontError:
                self._mark_dead(rep)
                continue  # one retry on a survivor
            finally:
                rep.outstanding -= 1
        return {
            "ok": False, "code": "fleet_down",
            "error": "no live replicas", "model": model or "default",
        }

    def stats(self) -> Dict[str, Any]:
        """The shared load-shedding view: per-replica front stats (queue
        depth, shed totals, compile-cache size, tenants) plus the live
        set.  Polling failures mark replicas dead — the router and the
        stats view agree on liveness."""
        per: Dict[str, Any] = {}
        for rep in self.replicas:
            if rep.dead or rep.client is None:
                per[str(rep.index)] = {"dead": True}
                continue
            try:
                per[str(rep.index)] = rep.client.stats()
            except FrontError:
                self._mark_dead(rep)
                per[str(rep.index)] = {"dead": True}
        return {
            "replicas": per,
            "live": self.live_count(),
            "total": len(self.replicas),
        }


# ---------------------------------------------------------------------------
# worker entry (one replica process)
# ---------------------------------------------------------------------------


def _worker_main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="keystone-fleet-worker")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--builder", required=True)
    ap.add_argument("--socket", required=True)
    ap.add_argument("--shapes", default=None)
    ap.add_argument("--coalesce-ms", type=float, default=None)
    ap.add_argument("--slo-ms", type=float, default=None)
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--hbm-mb", type=float, default=None)
    args = ap.parse_args(argv)
    if not args.worker:
        print("fleet.py is a worker entry: pass --worker (parents build "
              "Fleet objects)", file=sys.stderr)
        return 2

    # A serving replica is one dispatch-worker thread against a herd of
    # per-connection reader/writer threads that all wake when a batch
    # responds; at the 5 ms default GIL switch interval each wakeup
    # preempts the worker for a full slice between ITS dispatch steps.
    # 0.5 ms keeps handoffs short — a replica process owns its
    # interpreter, so this is process policy, not library policy.
    sys.setswitchinterval(0.0005)

    from keystone_tpu.serve.builders import build
    from keystone_tpu.serve.front import BatchingFront
    from keystone_tpu.serve.pool import ModelPool

    specs = build(args.builder)
    kwargs: Dict[str, Any] = {}
    if args.shapes is not None:
        kwargs["shapes"] = tuple(
            int(s) for s in args.shapes.split(",") if s.strip()
        )
    if args.coalesce_ms is not None:
        kwargs["coalesce_ms"] = args.coalesce_ms
    if args.slo_ms is not None:
        kwargs["slo_ms"] = args.slo_ms
    if args.queue_depth is not None:
        kwargs["queue_depth"] = args.queue_depth
    if args.hbm_mb is not None:
        kwargs["hbm_mb"] = args.hbm_mb
    first, rest = specs[0], specs[1:]
    gw = ModelPool(
        first.pipe, first.item_spec, name=first.name, **kwargs
    )
    for spec in rest:
        gw.add_model(
            spec.name, spec.pipe, spec.item_spec,
            slo_ms=spec.slo_ms, priority=spec.priority,
        )
    front = BatchingFront(gw, path=args.socket)
    print(f"READY {args.socket}", flush=True)
    try:
        sys.stdin.read()  # block until the parent closes our stdin
    except KeyboardInterrupt:
        pass
    front.close()
    gw.close(drain=False)
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(_worker_main(sys.argv[1:]))
