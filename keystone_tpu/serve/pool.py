"""Multi-tenant model pool: N fitted chains behind ONE gateway process.

The PR-14 :class:`~keystone_tpu.serve.gateway.Gateway` already hosts
multiple models in the PR-1 tiered cache, but its admission policy is
global: one hot tenant can fill the queue (starving everyone else) and
nothing bounds how much HBM the registered ladders may claim.  The pool
makes both into DECLARED policy, the same stance "Memory Safe Computations
with XLA Compiler" (PAPERS.md) takes for the solver: obligations are
computed up front, never discovered as OOM mid-flight.

1. **HBM-envelope admission** (``KEYSTONE_SERVE_HBM_MB`` / ``hbm_mb=``).
   :func:`ladder_peak_bytes` is the serving analogue of
   ``plan.block_solve_peak_bytes``: a closed-form bound over the model's
   resident leaves plus the widest stage boundary (operand + result) of the
   compiled ladder's LARGEST rung.  A model whose ladder provably overflows
   the declared envelope is registered cold — never warmed, every request
   rejected pre-dispatch with a structured ``rejected``/``kind='hbm'``
   response.  The overflow is a gate decision, not an OOM-retry outcome.

2. **LRU/priority eviction** over the PR-1 cache tiers.  Before each
   dispatch the worker checks the device-resident tenants' summed peak
   bytes against the envelope; the coldest (least-recently-requested),
   lowest-priority tenants are demoted HBM -> host
   (:meth:`IntermediateCache.demote`) until the hot model's ladder fits.
   A later request promotes a demoted model back — tier mechanics
   unchanged, the pool only chooses VICTIMS deliberately instead of
   sweeping the whole device tier.

3. **Per-tenant SLOs and fair shedding** (``KEYSTONE_SERVE_FAIR_FRAC``).
   Each tenant gets its own latency window/SLO and a fair share of the
   queue: with more than one tenant registered, a tenant may hold at most
   ``max(1, int(queue_depth * fair_frac))`` queued slots — a hot tenant
   saturates its share and sheds (``fair_share`` reason) while a cold
   tenant's occasional requests still admit.  One tenant cannot starve
   the rest by arrival rate alone.

Telemetry: ``serve.pool_peak_bytes{model}`` gauges,
``serve.shed_total{reason=fair_share|tenant_slo}``,
``serve.rejected{kind=hbm}``, ``serve.model_demotions`` — all per-process
registry series, queryable via :meth:`ModelPool.tenant_stats`.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from keystone_tpu.serve.gateway import (
    Gateway,
    ServeResponse,
    _ModelState,
)
from keystone_tpu.utils.logging import get_logger

logger = get_logger("keystone_tpu.serve.pool")

__all__ = ["ModelPool", "pool", "ladder_peak_bytes"]


def _leaf_bytes(tree) -> int:
    """Summed bytes of every array-shaped leaf (concrete or abstract)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def ladder_peak_bytes(node, item_spec, ladder, stages=None) -> int:
    """Closed-form peak-bytes bound for serving ``node`` through the
    compiled shape ladder — the serving analogue of
    ``plan.block_solve_peak_bytes`` (operand + result convention): the
    model's resident leaves plus, at the ladder's LARGEST rung, the widest
    consecutive (stage input + stage output) pair.  XLA's buffer assignment
    reuses everything beyond the live pair, so the bound is conservative
    but honest — the A5 IR-audit entry (``serve.pool_dispatch``) pins the
    compiled peak under it.

    ``stages`` (the contract stage graph) refines the bound via the shared
    ``analysis/contracts.propagate`` pass; without it the whole chain is
    treated as one stage (input + final output)."""
    from keystone_tpu.analysis import contracts

    model_bytes = _leaf_bytes(node)
    n = int(max(ladder))
    batch = jax.ShapeDtypeStruct(
        (n,) + tuple(item_spec.shape), np.dtype(item_spec.dtype)
    )
    boundary = 0
    if stages:
        try:
            records = contracts.propagate(stages, batch)
            boundary = max(
                _leaf_bytes(r.in_aval) + _leaf_bytes(r.out_aval)
                for r in records
            )
        except Exception as e:  # propagate refusal -> whole-chain fallback
            logger.warning(
                "ladder_peak_bytes: contract propagation failed (%s: %s); "
                "falling back to eval_shape", type(e).__name__, e,
            )
    if boundary == 0:
        out = jax.eval_shape(lambda x: node.apply_batch(x), batch)
        boundary = _leaf_bytes(batch) + _leaf_bytes(out)
    return model_bytes + boundary


@dataclass
class _Tenant:
    """Per-tenant accounting the pool layers over ``_ModelState``."""

    slo_ms: float
    priority: int = 0
    peak_bytes: int = 0
    over_envelope: bool = False
    last_used: float = 0.0
    served: int = 0
    shed: int = 0
    rejected: int = 0
    responses: int = 0
    slo_violations: int = 0  # ok-but-late + shed: burned SLO budget
    p99_ms: float = 0.0
    done: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=256)
    )


#: shed-flavored terminal codes (per-tenant shed_frac accounting); contract
#: rejections are counted separately — a malformed request is not overload.
_SHED_CODES = ("shed", "deadline", "breaker_open")


class ModelPool(Gateway):
    """A :class:`Gateway` with declared multi-tenant policy (module
    docstring): HBM-envelope admission, LRU/priority eviction over the
    cache tiers, per-tenant SLOs and fair-share shedding.  Build via
    :func:`pool`; register tenants with :meth:`add_model` (now accepting
    per-tenant ``slo_ms`` / ``priority``)."""

    def __init__(self, pipe, item_spec=None, *,
                 hbm_mb: Optional[float] = None,
                 fair_frac: Optional[float] = None,
                 **kwargs):
        from keystone_tpu.utils import knobs

        mb = float(hbm_mb if hbm_mb is not None
                   else knobs.get("KEYSTONE_SERVE_HBM_MB"))
        #: declared HBM envelope in bytes; 0 = unbounded (gateway behavior)
        self.hbm_bytes = int(mb * (1 << 20))
        self.fair_frac = float(
            fair_frac if fair_frac is not None
            else knobs.get("KEYSTONE_SERVE_FAIR_FRAC")
        )
        self._tenants: Dict[str, _Tenant] = {}
        # Gateway.__init__ registers the first model through our overridden
        # add_model, so the pool attributes above must already exist.
        super().__init__(pipe, item_spec, **kwargs)

    # -- registration ------------------------------------------------------

    def add_model(self, name: str, pipe, item_spec=None, warm: bool = True,
                  *, slo_ms: Optional[float] = None,
                  priority: int = 0) -> None:
        """Register a tenant: contract-check + store (the Gateway path),
        compute its ladder-peak bound, and gate it against the declared
        HBM envelope.  An over-envelope tenant is NEVER warmed (warming
        would dispatch exactly the program the envelope says cannot fit);
        its requests reject pre-dispatch with ``kind='hbm'``."""
        super().add_model(name, pipe, item_spec, warm=False)
        state = self._nodes_spec[name]
        hit, node = self._pool.lookup(self._pool_key(name))
        assert hit, f"model {name!r} vanished between put and lookup"
        peak = ladder_peak_bytes(
            node, state.item_spec, self._full_ladder, stages=state.stages
        )
        over = self.hbm_bytes > 0 and peak > self.hbm_bytes
        with self._cond:
            self._tenants[name] = _Tenant(
                slo_ms=float(slo_ms if slo_ms is not None else self.slo_ms),
                priority=int(priority), peak_bytes=peak, over_envelope=over,
            )
        reg = self._registry()
        reg.set_gauge("serve.pool_peak_bytes", float(peak), model=name)
        if over:
            logger.warning(
                "model %s ladder peak %d B exceeds the declared HBM "
                "envelope %d B: registered cold, requests will reject "
                "pre-dispatch (kind='hbm')", name, peak, self.hbm_bytes,
            )
        elif warm:
            self._warmup(name, node, state.item_spec)

    # -- admission ---------------------------------------------------------

    def _tenant_gate(self, state: _ModelState, model: str,
                     now: float) -> Optional[ServeResponse]:
        ts = self._tenants.get(model)
        if ts is None:
            return None
        reg = self._registry()
        ts.last_used = now
        if ts.over_envelope:
            reg.inc("serve.rejected", kind="hbm")
            return ServeResponse(
                ok=False, code="rejected", kind="hbm",
                error=(
                    f"ladder peak {ts.peak_bytes} B exceeds the declared "
                    f"HBM envelope {self.hbm_bytes} B "
                    "(KEYSTONE_SERVE_HBM_MB) — rejected pre-dispatch"
                ),
                model=model,
            )
        if len(self._tenants) > 1 and self.fair_frac > 0:
            cap = max(1, int(self.queue_depth * self.fair_frac))
            queued = sum(1 for r in self._queue if r.model == model)
            if queued >= cap:
                reg.inc("serve.shed_total", reason="fair_share")
                return ServeResponse(
                    ok=False, code="shed",
                    error=f"tenant queue share full ({queued}/{cap})",
                    retry_after_s=round(max(
                        cap * max(self._p50_ms, 1.0) / 1e3,
                        ts.slo_ms / 1e3,
                    ), 3),
                    model=model,
                )
        if ts.p99_ms > ts.slo_ms and any(
            r.model == model for r in self._queue
        ):
            reg.inc("serve.shed_total", reason="tenant_slo")
            return ServeResponse(
                ok=False, code="shed",
                error=(f"tenant p99 {ts.p99_ms:.1f}ms over its "
                       f"{ts.slo_ms:.1f}ms SLO"),
                retry_after_s=round(ts.slo_ms / 1e3, 3), model=model,
            )
        return None

    # -- eviction ----------------------------------------------------------

    def _fetch_model(self, name: str):
        if self.hbm_bytes > 0:
            self._evict_for(name)
        return super()._fetch_model(name)

    def _evict_for(self, hot: str) -> int:
        """LRU/priority eviction: demote cold tenants' device-tier entries
        until the device-resident peak-bytes sum (hot model included) fits
        the declared envelope.  Victim order: lowest priority first, then
        least-recently-requested."""
        with self._cond:
            hot_ts = self._tenants.get(hot)
            total = hot_ts.peak_bytes if hot_ts is not None else 0
            resident: List[Tuple[int, float, str, int]] = []
            for name, ts in self._tenants.items():
                if name == hot:
                    continue
                if self._pool.tier_of(self._pool_key(name)) == "device":
                    resident.append(
                        (ts.priority, ts.last_used, name, ts.peak_bytes)
                    )
            total += sum(p for _, _, _, p in resident)
            if total <= self.hbm_bytes:
                return 0
            resident.sort()
            demoted = 0
            for _, _, name, peak in resident:
                if total <= self.hbm_bytes:
                    break
                if self._pool.demote(self._pool_key(name)):
                    total -= peak
                    demoted += 1
        if demoted:
            self._registry().inc("serve.model_demotions", demoted)
            logger.info(
                "HBM envelope pressure: demoted %d cold tenant(s) for %s",
                demoted, hot,
            )
        return demoted

    # -- per-tenant accounting --------------------------------------------

    def _note_outcome(self, model: str, resp: ServeResponse) -> None:
        ts = self._tenants.get(model)
        if ts is None:
            return
        reg = self._registry()
        ts.responses += 1
        reg.inc("serve.tenant_responses", model=model)
        if resp.ok:
            ts.served += 1
            reg.inc("serve.tenant_served", model=model)
            ts.done.append((time.monotonic(), resp.latency_ms))
            if resp.latency_ms is not None and resp.latency_ms > ts.slo_ms:
                # served, but late: the request still burned SLO budget
                ts.slo_violations += 1
                reg.inc("serve.tenant_slo_violations", model=model)
            if ts.served % 8 == 0:
                self._refresh_tenant(ts)
        elif resp.code in _SHED_CODES:
            ts.shed += 1
            ts.slo_violations += 1
            reg.inc("serve.tenant_shed", model=model)
            reg.inc("serve.tenant_slo_violations", model=model)
        elif resp.code == "rejected":
            ts.rejected += 1

    @staticmethod
    def _refresh_tenant(ts: _Tenant) -> None:
        now = time.monotonic()
        window = sorted(l for t, l in ts.done if now - t <= 5.0)
        if window:
            ts.p99_ms = window[min(len(window) - 1,
                                   int(0.99 * len(window)))]

    def _respond(self, req, resp: ServeResponse) -> None:
        super()._respond(req, resp)
        self._note_outcome(req.model, resp)

    def _finish(self, pending):
        pending = super()._finish(pending)
        resp = pending._response
        if resp is not None:
            # submit-path terminals (gate sheds / rejections) never reach
            # _respond; ok responses never come through here
            self._note_outcome(resp.model, resp)
        return pending

    def tenant_stats(self, model: Optional[str] = None) -> dict:
        """Per-tenant accounting (one tenant, or all keyed by name):
        served/shed/rejected counts, shed fraction, the tenant's own
        p99/SLO, its declared ladder-peak bytes and envelope verdict, and
        its current cache tier."""
        with self._cond:
            if model is None:
                names = list(self._tenants)
            else:
                names = [model]
            out = {}
            for name in names:
                ts = self._tenants[name]
                self._refresh_tenant(ts)
                out[name] = {
                    "served": ts.served,
                    "shed": ts.shed,
                    "rejected": ts.rejected,
                    "responses": ts.responses,
                    "shed_frac": round(
                        ts.shed / max(ts.responses, 1), 4
                    ),
                    "slo_violations": ts.slo_violations,
                    "slo_violation_frac": round(
                        ts.slo_violations / max(ts.responses, 1), 4
                    ),
                    "p99_ms": round(ts.p99_ms, 3),
                    "slo_ms": ts.slo_ms,
                    "priority": ts.priority,
                    "peak_bytes": ts.peak_bytes,
                    "over_envelope": ts.over_envelope,
                    "tier": self._pool.tier_of(self._pool_key(name)),
                }
            return out[model] if model is not None else out

    def stats(self) -> dict:
        s = super().stats()
        s["hbm_envelope_bytes"] = self.hbm_bytes
        s["fair_frac"] = self.fair_frac
        s["tenants"] = self.tenant_stats()
        return s


def pool(pipe, item_spec=None, **kwargs) -> ModelPool:
    """Build a :class:`ModelPool` over a fitted pipeline.  Accepts every
    :func:`keystone_tpu.serve.serve` keyword plus ``hbm_mb`` /
    ``KEYSTONE_SERVE_HBM_MB`` (declared HBM envelope, 0 = unbounded) and
    ``fair_frac`` / ``KEYSTONE_SERVE_FAIR_FRAC`` (per-tenant queue share
    with >1 tenant registered, 0 disables).  Register further tenants with
    :meth:`ModelPool.add_model`, which gains per-tenant ``slo_ms`` and
    ``priority``."""
    return ModelPool(pipe, item_spec, **kwargs)
