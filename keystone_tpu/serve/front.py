"""Cross-process request batching front: many client PROCESSES, one
micro-batch ladder.

One gateway is one process, so until this module every client of the
compiled ladder lived in the server's interpreter and per-request dispatch
overhead dominated the saturation knee (BENCH_r08: ~188 QPS, host-bound).
:class:`BatchingFront` listens on an AF_UNIX socket and funnels each
connection's requests into ONE gateway's queue, where the existing
coalesce window batches them ACROSS connections — N single-request client
processes turn into padded micro-batches on the ladder, exactly the
dispatch amortization the in-process path already had.

Wire protocol (local IPC only — a unix socket owned by the serving user;
pickle is acceptable in that trust domain, documented here on purpose):
4-byte big-endian length prefix + pickled dict.  Requests:
``{"op": "predict", "id": n, "x": ndarray, "deadline_ms": f|None,
"model": str|None, "trace": str|None}`` or ``{"op": "stats", "id": n}``.
The optional ``trace`` field carries a request-scoped trace id (see
``keystone_tpu.telemetry.trace``) across the process boundary: the
server's reader thread hands it to ``gateway.submit``, and the response
dict echoes it back as ``trace`` — so a client-minted id stitches front
enqueue, gateway admission, dispatch and reply spans from BOTH processes
into one Perfetto trace.  Responses mirror
:class:`~keystone_tpu.serve.gateway.ServeResponse` as a plain dict (values
as numpy) so CLIENTS NEED NO JAX — this module imports only
stdlib + numpy at the top level, and ``scripts/front_client.py`` loads it
standalone for the bench's closed-loop driver subprocesses (telemetry
spans are imported lazily and only server-side).

Per connection the front runs a reader thread (decode -> ``gateway.
submit`` — admission happens on the reader, so sheds/rejections cost no
worker time) and a writer thread (resolve pending futures in FIFO order,
encode, write back).  The no-wedge contract is inherited: every submitted
request terminates in a structured response, so the writer never blocks
forever.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu.utils.lockwitness import register_lock

__all__ = [
    "BatchingFront", "FrontClient", "FrontError", "drive_main",
    "mint_trace_id",
]

_LEN = struct.Struct(">I")
_MAX_MSG = 64 << 20  # 64 MiB: a corrupt length prefix must not OOM us


def mint_trace_id() -> str:
    """A compact request trace id (16 hex chars) — pure stdlib, so jax-free
    standalone clients can mint one without importing ``keystone_tpu``.
    Same format as ``keystone_tpu.telemetry.trace.mint``."""
    return os.urandom(8).hex()


def _request_span(name: str, trace_id, **args):
    """Server-side span hook: resolves the telemetry tracer lazily so this
    module stays importable with stdlib+numpy only (standalone clients
    never enter spans — the server process always has the package)."""
    if trace_id is None:
        return _NULL_CM
    try:
        from keystone_tpu.telemetry.trace import request_span
    except ImportError:  # standalone load: no keystone_tpu on the path
        return _NULL_CM
    return request_span(name, trace_id, **args)


class _NullCM:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullCM()


class FrontError(ConnectionError):
    """Socket-level failure talking to a front (server died, bad frame)."""


def _send_msg(sock: socket.socket, obj: Any, lock=None) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _LEN.pack(len(payload)) + payload
    if lock is not None:
        with lock:
            # lint: disable=T2 (the lock exists to serialize whole frames
            # onto one socket — sendall under it IS the framing contract;
            # a stalled peer stalls only this connection's writers)
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise FrontError("connection closed mid-frame")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_MSG:
        raise FrontError(f"frame length {n} exceeds {_MAX_MSG}")
    return pickle.loads(_recv_exact(sock, n))


def default_socket_path(tag: str = "front") -> str:
    return os.path.join(
        tempfile.gettempdir(), f"keystone-{tag}-{os.getpid()}.sock"
    )


class BatchingFront:
    """Serve a gateway (or :class:`~keystone_tpu.serve.pool.ModelPool`)
    over an AF_UNIX socket (module docstring).  ``path`` is created fresh
    (a stale socket file is unlinked); :meth:`close` unlinks it again."""

    def __init__(self, gateway, path: Optional[str] = None,
                 result_timeout_s: float = 30.0):
        self.gateway = gateway
        self.path = path or default_socket_path()
        self._result_timeout_s = float(result_timeout_s)
        self._closing = False
        self._conns: List[socket.socket] = []
        self._lock = register_lock(threading.Lock(), "serve.front.batching")
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.path)
        self._srv.listen(64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="keystone-front-accept",
            daemon=True,
        )
        self._accept_thread.start()

    # -- server loops ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # closed
            with self._lock:
                self._conns.append(conn)
            # per-connection FIFO of (req_id, PendingResponse): the reader
            # feeds it, the writer drains it — responses go back in request
            # order, so the sync client's next frame is always its own
            fifo: List[Tuple[int, Any]] = []
            cond = threading.Condition()
            threading.Thread(
                target=self._reader, args=(conn, fifo, cond),
                name="keystone-front-reader", daemon=True,
            ).start()
            threading.Thread(
                target=self._writer, args=(conn, fifo, cond),
                name="keystone-front-writer", daemon=True,
            ).start()

    def _reader(self, conn: socket.socket, fifo, cond) -> None:
        try:
            while True:
                msg = _recv_msg(conn)
                op = msg.get("op")
                if op == "predict":
                    tid = msg.get("trace")
                    with _request_span("front.enqueue", tid,
                                       model=msg.get("model") or ""):
                        pending = self.gateway.submit(
                            msg["x"], deadline_ms=msg.get("deadline_ms"),
                            model=msg.get("model"), trace_id=tid,
                        )
                    with cond:
                        fifo.append((msg.get("id"), pending))
                        cond.notify()
                elif op == "stats":
                    with cond:
                        fifo.append((msg.get("id"), self._stats()))
                        cond.notify()
                else:
                    with cond:
                        fifo.append((msg.get("id"), {
                            "ok": False, "code": "error",
                            "error": f"unknown op {op!r}",
                        }))
                        cond.notify()
        except (FrontError, OSError, EOFError, pickle.UnpicklingError):
            pass  # client went away; the writer drains what was admitted
        finally:
            with cond:
                fifo.append((None, None))  # writer stop marker
                cond.notify()

    def _writer(self, conn: socket.socket, fifo, cond) -> None:
        try:
            while True:
                with cond:
                    while not fifo:
                        cond.wait(0.1)
                    req_id, item = fifo.pop(0)
                if item is None:
                    return  # reader ended
                if isinstance(item, dict):  # stats / error passthrough
                    payload = dict(item, id=req_id)
                else:
                    resp = item.result(self._result_timeout_s)
                    payload = self._encode(resp, req_id)
                _send_msg(conn, payload)
        except (OSError, BrokenPipeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    @staticmethod
    def _encode(resp, req_id) -> Dict[str, Any]:
        value = resp.value
        if value is not None:
            # device -> host on the FRONT thread, never the dispatch worker
            value = np.asarray(value)
        return {
            "id": req_id, "ok": resp.ok, "code": resp.code, "value": value,
            "error": resp.error, "kind": resp.kind, "stage": resp.stage,
            "retry_after_s": resp.retry_after_s,
            "latency_ms": resp.latency_ms, "model": resp.model,
            "trace": getattr(resp, "trace_id", None),
        }

    def _stats(self) -> Dict[str, Any]:
        gw = self.gateway
        models = {
            name: {
                "shape": list(st.item_spec.shape),
                "dtype": np.dtype(st.item_spec.dtype).name,
            }
            for name, st in gw._nodes_spec.items()
        }
        out = {
            "id": None, "ok": True, "code": "stats",
            "stats": gw.stats(),
            "models": models,
            "est_one_ms": {
                name: gw._estimate_ms(name, 1)
                for name in gw._nodes_spec
            },
            "compile_cache_size": gw.compile_cache_size(),
            "pid": os.getpid(),
        }
        return out

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class FrontClient:
    """Synchronous, jax-free client of a :class:`BatchingFront` socket:
    one outstanding request per connection (cross-process batching comes
    from MANY client processes, each sync — the open-loop shape real
    single-request traffic has).  Thread-safe via an internal lock."""

    def __init__(self, path: str, timeout_s: float = 30.0):
        self.path = path
        self._timeout_s = float(timeout_s)
        self._lock = register_lock(threading.Lock(), "serve.front.client")
        self._next_id = 0
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(self._timeout_s)
        try:
            self._sock.connect(path)
        except OSError as e:
            raise FrontError(f"cannot connect to {path}: {e}") from e

    def _call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._next_id += 1
            msg["id"] = self._next_id
            try:
                _send_msg(self._sock, msg)
                while True:
                    resp = _recv_msg(self._sock)
                    if resp.get("id") == msg["id"]:
                        return resp
            except (OSError, EOFError, pickle.UnpicklingError) as e:
                raise FrontError(
                    f"front at {self.path} unreachable: "
                    f"{type(e).__name__}: {e}"
                ) from e

    def predict(self, x, deadline_ms: Optional[float] = None,
                model: Optional[str] = None,
                trace_id: Optional[str] = None) -> Dict[str, Any]:
        """One request -> the structured response dict (``ok``/``code``/
        ``value``/...).  Raises :class:`FrontError` only for SOCKET
        failures; sheds and rejections come back as structured dicts.
        Pass ``trace_id`` (e.g. :func:`mint_trace_id`) to stitch the
        server-side spans for THIS request into a distributed trace; it
        is echoed back in the response's ``trace`` field."""
        return self._call({
            "op": "predict", "x": np.asarray(x),
            "deadline_ms": deadline_ms, "model": model,
            "trace": trace_id,
        })

    def stats(self) -> Dict[str, Any]:
        return self._call({"op": "stats"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# closed-loop driver (the bench fleet regime's client subprocess)
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def drive_main(argv: List[str]) -> int:
    """Closed-loop load driver: connect to a front socket, discover the
    model's item shape from the stats op, then keep ``--window``
    outstanding requests pipelined on the one connection for
    ``--seconds`` and print ONE JSON line of client-side results (ok/shed
    counts, wall, qps, p50/p99 end-to-end ms).  ``--window 1`` is the
    strict sync request/response loop; a larger window is how a real
    multi-request client process offers concurrency WITHOUT a process per
    in-flight request — the server-side coalesce then batches the window
    across client processes.  No jax — ``scripts/front_client.py`` runs
    this in a plain numpy process."""
    import argparse
    import heapq
    import json

    ap = argparse.ArgumentParser(prog="front_client")
    ap.add_argument("--drive", required=True, help="front socket path")
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--window", type=int, default=1,
                    help="outstanding requests kept in flight")
    ap.add_argument("--model", default=None)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)

    client = FrontClient(args.drive)
    info = client.stats()
    models = info.get("models", {})
    model = args.model or next(iter(models))
    spec = models[model]
    rng = np.random.default_rng(
        args.seed if args.seed is not None else os.getpid()
    )
    item = rng.standard_normal(spec["shape"]).astype(spec["dtype"])

    sock = client._sock
    sent: Dict[int, float] = {}  # id -> send time
    next_id = [0]

    def send_one() -> None:
        next_id[0] += 1
        _send_msg(sock, {
            "op": "predict", "id": next_id[0], "x": item,
            "deadline_ms": args.deadline_ms, "model": model,
        })
        sent[next_id[0]] = time.perf_counter()

    n_ok = n_shed = n_other = 0
    lats: List[float] = []
    paused: List[float] = []  # due times of shed slots (a heap)
    t0 = time.perf_counter()
    err: Optional[str] = None
    try:
        for _ in range(max(1, args.window)):
            send_one()
        while time.perf_counter() - t0 < args.seconds:
            # resume shed slots whose retry-after elapsed; if EVERY slot
            # is paused there is nothing to recv, so sleep to the next due
            now = time.perf_counter()
            while paused and paused[0] <= now:
                heapq.heappop(paused)
                send_one()
            if not sent:
                if paused:
                    time.sleep(min(max(paused[0] - now, 0.0), 0.05))
                    continue
                send_one()
            resp = _recv_msg(sock)
            t1 = sent.pop(resp.get("id"), None)
            dt_ms = ((time.perf_counter() - t1) * 1e3
                     if t1 is not None else 0.0)
            if resp.get("ok"):
                n_ok += 1
                lats.append(dt_ms)
                send_one()
            elif resp.get("code") == "shed":
                # honor retry_after_s (capped): a slot that resent
                # immediately would feed the overload that shed it —
                # the sync loop's backoff, pipelined form
                n_shed += 1
                ra = float(resp.get("retry_after_s") or 0.01)
                heapq.heappush(
                    paused, time.perf_counter() + min(ra, 0.05)
                )
            else:
                n_other += 1
                send_one()
        while sent:  # drain the tail; past the window, not counted
            resp = _recv_msg(sock)
            sent.pop(resp.get("id"), None)
    except (FrontError, OSError, EOFError, pickle.UnpicklingError) as e:
        err = str(e)  # server died mid-drive: report what we measured
    wall = time.perf_counter() - t0
    lats.sort()
    print(json.dumps({
        "n_ok": n_ok, "n_shed": n_shed, "n_other": n_other,
        "wall_s": round(wall, 3),
        "qps": round(n_ok / wall, 2) if wall > 0 else 0.0,
        "p50_ms": _percentile(lats, 0.50),
        "p99_ms": _percentile(lats, 0.99),
        "model": model,
        "error": err,
    }), flush=True)
    client.close()
    return 0 if err is None else 3


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    import sys

    sys.exit(drive_main(sys.argv[1:]))
