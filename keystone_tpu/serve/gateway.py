"""Hardened serving tier: an admission-checked prediction gateway with
deadline-aware load shedding and graceful degradation.

The north star is serving millions of users; until this module every fitted
pipeline only ran batch fit/eval.  KeystoneML ``Transformer``s are pure
per-item functions (PAPER.md core-API layer), so a compiled, fixed-shape
serve path is natural: :func:`serve` compiles the fitted apply-chain ONCE at
a small ladder of fixed micro-batch shapes (padded dispatch, donated input
buffers) and fronts it with the robustness substrate PRs 10-13 built:

1. **Admission control** (the PR-10 follow-on): every request is validated
   against the chain's input contract — the same
   ``analysis/contracts.propagate`` pass the checker and planner share —
   *at the gate*.  A bad rank/dtype/dim is rejected with a structured
   response naming the contract kind and the stage that would have choked,
   never discovered inside a donated-buffer dispatch.  "Memory Safe
   Computations with XLA Compiler" (PAPERS.md) motivates the stance:
   reject work the compiled program cannot safely hold *before* dispatch,
   not via OOM mid-flight — the gateway only ever dispatches the shapes it
   compiled.

2. **Deadline-aware coalescing and load shedding.**  A bounded queue
   (``KEYSTONE_SERVE_QUEUE_DEPTH``) batches compatible requests up the
   shape ladder; work whose deadline has passed — or provably cannot be
   met given the measured per-shape dispatch estimate — is dropped with a
   structured ``deadline`` shed before it wastes device time, and once
   queue depth or the observed p99 crosses the SLO
   (``KEYSTONE_SERVE_SLO_MS``) new arrivals shed with a ``retry_after_s``
   signal.  Overload degrades to partial availability, never collapse.

3. **Graceful degradation ladder.**  Cold fitted models live in the PR-1
   tiered intermediate cache (HBM -> host): overload demotes them, an
   OOM-flavored dispatch error runs the PR-12 retry hook
   (``retry.default_on_retry`` — frees the active intermediate cache's
   device tier), releases the model pool's device tier, and SHRINKS the
   batch-shape ladder (``serve.degraded``) so the retry dispatches a
   smaller program.  A per-model circuit breaker rides the PR-13 health
   sentinels: a dispatch whose outputs go non-finite is quarantined (its
   requests fail fast with a ``sentinel`` response — NaNs are never
   served), ``KEYSTONE_SERVE_BREAKER`` consecutive trips open the breaker,
   and after a cooldown a half-open probe re-admits the model.

4. **Chaos integration.**  ``KEYSTONE_FAULTS`` gained ``serve.admit`` /
   ``serve.dispatch`` / ``serve.respond`` sites (``utils/faults.py``);
   ``scripts/serve_chaos_smoke.py`` fires all three plus a mid-run SIGKILL
   under sustained synthetic load and asserts availability degrades
   gracefully — every request gets a response or a structured shed, the
   breaker round-trips open -> half-open -> closed, and the restarted
   gateway serves with zero steady-state recompiles.

Telemetry: ``serve.qps`` / ``serve.p99_ms`` / ``serve.breaker_state``
gauges, ``serve.shed_total{reason}`` / ``serve.degraded`` counters, plus
request/response/dispatch series — all queryable via the process registry
(no log scraping).
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.telemetry.registry import LATENCY_BUCKETS_MS
from keystone_tpu.telemetry.trace import maybe_mint, request_span
from keystone_tpu.utils.logging import get_logger

logger = get_logger("keystone_tpu.serve")

__all__ = [
    "serve",
    "Gateway",
    "ServeResponse",
    "ServeRejected",
    "PendingResponse",
    "DEFAULT_SHAPES",
]

#: default micro-batch shape ladder (overridden by KEYSTONE_SERVE_SHAPES
#: or the ``shapes=`` argument): 1 covers interactive single items, the
#: larger rungs amortize dispatch for coalesced bursts.
DEFAULT_SHAPES: Tuple[int, ...] = (1, 8, 32)

#: response codes (the structured-availability vocabulary): every submitted
#: request terminates in exactly one of these.
CODES: Tuple[str, ...] = (
    "ok",           # served
    "rejected",     # admission: contract violation at the gate
    "shed",         # overload: queue depth / p99-over-SLO (retry_after_s set)
    "deadline",     # the request's deadline passed or provably cannot be met
    "breaker_open", # circuit breaker fast-fail (retry_after_s set)
    "sentinel",     # dispatch output tripped the non-finite sentinel
    "error",        # gateway-internal failure (injected faults land here)
    "shutdown",     # gateway closed before the request could be served
)


def _serve_apply(node, xs):
    """THE fixed-shape serve dispatch program (also the ``serve.dispatch``
    IR-audit entry point, ``analysis/ir_audit.py``): one fused apply-chain
    over one padded micro-batch.  Kept as a named pure function so the
    audit lowers the identical program the jitted entry below traces."""
    return node.apply_batch(xs)


#: the gateway's one compiled dispatch entry: cache keyed on the model's
#: pytree structure + the (fixed) batch aval, input buffer DONATED — the
#: padded batch is constructed per dispatch and never reused, so its HBM
#: is returned to the output.  Steady-state serving holds this function's
#: compile-cache size constant (the zero-recompile pin in tests/smokes).
_jit_apply_batch = jax.jit(_serve_apply, donate_argnums=(1,))


@functools.partial(jax.jit, static_argnames=("n",))
def _pad_rows(xs, n: int):
    """Zero-pad a stacked batch up to ladder shape ``n`` (rows are
    independent per-item programs; padding rows are sliced off after)."""
    pad = n - xs.shape[0]
    return jnp.concatenate(
        [xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)], axis=0
    )


@jax.jit
def _finite_flag(out):
    """Device-side health sentinel over a dispatch output: True iff every
    floating leaf is finite (the PR-13 NaN/divergence check, serving
    form).  One scalar; synced at response time — serving already syncs."""
    flags = [
        jnp.all(jnp.isfinite(l))
        for l in jax.tree_util.tree_leaves(out)
        if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
    ]
    if not flags:
        return jnp.bool_(True)
    return functools.reduce(jnp.logical_and, flags)


@dataclass(frozen=True)
class ServeResponse:
    """One request's terminal outcome. ``ok`` iff ``code == 'ok'``;
    non-ok responses are STRUCTURED: ``kind``/``stage`` carry the
    contract-issue classification for admission rejects, ``retry_after_s``
    the back-off signal for sheds and open-breaker fast-fails."""

    ok: bool
    code: str
    value: Any = None
    error: Optional[str] = None
    kind: Optional[str] = None      # contract-issue kind: rank|dtype|dim
    stage: Optional[str] = None     # stage the contract pass attributes
    retry_after_s: Optional[float] = None
    latency_ms: Optional[float] = None
    model: str = "default"
    trace_id: Optional[str] = None  # request-scoped trace id (when sampled)


class ServeRejected(RuntimeError):
    """Raised by :meth:`Gateway.predict` for any non-ok response; carries
    the structured :class:`ServeResponse` as ``.response``."""

    def __init__(self, response: ServeResponse):
        super().__init__(
            f"serve request {response.code}"
            + (f": {response.error}" if response.error else "")
        )
        self.response = response


class PendingResponse:
    """A submitted request's future. ``result(timeout)`` blocks for the
    terminal :class:`ServeResponse`; an elapsed timeout returns a
    structured non-ok response instead of raising (the caller always gets
    a response — the no-wedge contract)."""

    __slots__ = ("_event", "_response")

    def __init__(self):
        self._event = threading.Event()
        self._response: Optional[ServeResponse] = None

    def _resolve(self, response: ServeResponse) -> None:
        if self._response is None:
            self._response = response
            self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResponse:
        if not self._event.wait(timeout):
            return ServeResponse(
                ok=False, code="error",
                error=f"no response within {timeout}s (gateway busy/stopped)",
            )
        return self._response


def _resolved(response: ServeResponse) -> PendingResponse:
    p = PendingResponse()
    p._resolve(response)
    return p


@dataclass
class _Request:
    x: Any
    model: str
    pending: PendingResponse
    t_submit: float
    deadline_t: Optional[float]  # absolute monotonic deadline, None = none
    probe: bool = False
    trace_id: Optional[str] = None


@dataclass
class _ModelState:
    """Per-model breaker + admission metadata."""

    item_spec: Any                      # ShapeDtypeStruct of ONE item
    stages: List[Tuple[Any, Tuple[int, ...]]]
    breaker: str = "closed"             # closed | open | half_open
    trips: int = 0                      # consecutive sentinel trips
    t_open: float = 0.0
    probe_inflight: bool = False


def _knob_default(value, knob_name: str):
    from keystone_tpu.utils import knobs

    return value if value is not None else knobs.get(knob_name)


def _mb(name: str) -> int:
    from keystone_tpu.utils import knobs

    return int(knobs.get(name)) << 20


class Gateway:
    """A long-lived, multi-tenant prediction gateway over fitted pipelines
    (module docstring).  Build via :func:`serve`; serve via
    :meth:`predict` (sync) or :meth:`submit` (future).  Thread-safe:
    submissions may come from any thread; ONE worker thread owns every
    jax dispatch (single-trace discipline)."""

    def __init__(
        self,
        pipe,
        item_spec=None,
        *,
        name: str = "default",
        shapes: Optional[Sequence[int]] = None,
        slo_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        breaker_threshold: Optional[int] = None,
        breaker_cooldown_s: float = 0.25,
        retries: Optional[int] = None,
        backoff_s: float = 0.05,
        coalesce_ms: float = 1.0,
        warm: bool = True,
        start: bool = True,
    ):
        from keystone_tpu.utils import knobs

        raw_shapes = shapes if shapes is not None else knobs.get(
            "KEYSTONE_SERVE_SHAPES"
        )
        ladder = tuple(sorted(set(int(s) for s in (raw_shapes or
                                                   DEFAULT_SHAPES))))
        if not ladder or any(s < 1 for s in ladder):
            raise ValueError(f"serve shapes must be positive ints: {ladder}")
        self._ladder: Tuple[int, ...] = ladder
        self._full_ladder = ladder  # for stats/debug after degradation
        self.slo_ms = float(_knob_default(slo_ms, "KEYSTONE_SERVE_SLO_MS"))
        self.queue_depth = int(
            _knob_default(queue_depth, "KEYSTONE_SERVE_QUEUE_DEPTH")
        )
        self.breaker_threshold = int(
            _knob_default(breaker_threshold, "KEYSTONE_SERVE_BREAKER")
        )
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._retries = retries
        self._backoff_s = float(backoff_s)
        self._coalesce_s = float(coalesce_ms) / 1e3

        # model pool: the PR-1 tiered cache holds every fitted model;
        # lookups promote toward HBM, pressure demotes cold models to host
        from keystone_tpu.core.cache import IntermediateCache

        self._pool = IntermediateCache(
            device_bytes=_mb("KEYSTONE_CACHE_DEVICE_MB"),
            host_bytes=_mb("KEYSTONE_CACHE_HOST_MB"),
            disk_bytes=0, cache_dir=None, sync_on_compute=False,
        )
        self._nodes_spec: Dict[str, _ModelState] = {}

        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._closing = False
        self._stopped = False
        self._worker: Optional[threading.Thread] = None
        self._active_model: Optional[str] = None

        # observed latency window -> qps/p50/p99 gauges + the shed signal
        self._done: collections.deque = collections.deque(maxlen=512)
        self._p50_ms = 0.0
        self._p99_ms = 0.0
        self._est_ms: Dict[Tuple[str, int], float] = {}  # (model, shape)
        # shed-path demotion gate: True while a demote sweep may still
        # find device-tier victims (re-armed when a lookup can promote)
        self._demote_armed = True
        self._lat_pending = 0          # ok responses since the last
        self._lat_refreshed = 0.0      # windowed-percentile refresh

        self.add_model(name, pipe, item_spec, warm=warm)
        self.default_model = name
        if start:
            self.start()

    # -- model pool --------------------------------------------------------

    @staticmethod
    def _pool_key(name: str) -> str:
        return f"serve.model:{name}"

    def add_model(self, name: str, pipe, item_spec=None,
                  warm: bool = True) -> None:
        """Register a fitted pipeline under ``name``: contract-check the
        whole chain at the ladder's largest shape (a broken chain is
        rejected HERE, not at the first request), store it in the tiered
        model pool, and (``warm=True``) compile every ladder shape."""
        from keystone_tpu.analysis import contracts

        node, stages = _dispatchable(pipe)
        spec = _resolve_item_spec(item_spec, stages)
        batch = jax.ShapeDtypeStruct(
            (self._ladder[-1],) + tuple(spec.shape), spec.dtype
        )
        records = contracts.propagate(stages, batch)
        bad = [r for r in records if r.issue is not None]
        if bad:
            lines = [
                f"{r.name}: [{r.issue.kind}] {r.issue.message}" for r in bad
            ]
            raise contracts.ContractViolation(
                f"serve({name!r}): the pipeline cannot serve its declared "
                "input contract:\n  " + "\n  ".join(lines), [],
            )
        with self._cond:
            self._nodes_spec[name] = _ModelState(
                item_spec=spec, stages=stages,
            )
        self._pool.put(self._pool_key(name), node, cost_s=1.0)
        if warm:
            self._warmup(name, node, spec)
        self._registry().set_gauge("serve.breaker_state", 0.0, model=name)

    def _fetch_model(self, name: str):
        hit, node = self._pool.lookup(self._pool_key(name))
        if not hit:
            raise KeyError(
                f"model {name!r} no longer resident (evicted from every "
                "cache tier — grow KEYSTONE_CACHE_HOST_MB)"
            )
        # the lookup may have promoted the model back to the device
        # tier, so a later shed-path demote sweep can find victims again
        self._demote_armed = True
        return node

    def _warmup(self, name: str, node, spec) -> None:
        """Compile the dispatch program at every ladder shape with a zero
        batch, so steady-state serving performs ZERO compiles (and record
        the per-shape latency estimate the deadline filter uses)."""
        for n in self._ladder:
            # first call includes compile; the second times the steady
            # state for the deadline filter's per-shape estimate
            jax.block_until_ready(_jit_apply_batch(
                node, jnp.zeros((n,) + tuple(spec.shape), spec.dtype)
            ))
            xs = jnp.zeros((n,) + tuple(spec.shape), spec.dtype)
            t0 = time.perf_counter()
            jax.block_until_ready(_jit_apply_batch(node, xs))
            self._est_ms[(name, n)] = (time.perf_counter() - t0) * 1e3

    # -- admission ---------------------------------------------------------

    def _admit_issue(self, x, state: _ModelState) -> Optional[ServeResponse]:
        """None = admitted; else the structured rejection.  The shape/dtype
        gate compares against the model's item spec (the compiled-ladder
        contract); on mismatch the shared contracts pass attributes the
        failure to the stage whose declared contract the request breaks."""
        spec = state.item_spec
        shape = tuple(getattr(x, "shape", ()))
        dtype = getattr(x, "dtype", None)
        kind = None
        if dtype is None or np.dtype(dtype) != np.dtype(spec.dtype):
            # the C4 family at the gate: an f64 (or integer) item under the
            # compiled f32 program is rejected pre-dispatch, never silently
            # cast inside a donated buffer
            kind = "dtype"
            msg = (f"expects {np.dtype(spec.dtype).name} items, got "
                   f"{np.dtype(dtype).name if dtype is not None else '?'}")
        elif len(shape) != len(spec.shape):
            kind = "rank"
            msg = (f"expects rank-{len(spec.shape)} items "
                   f"{tuple(spec.shape)}, got rank-{len(shape)} {shape}")
        elif shape != tuple(spec.shape):
            kind = "dim"
            msg = (f"compiled shape ladder serves items {tuple(spec.shape)}, "
                   f"got {shape}")
        if kind is None:
            return None
        stage, detail = _attribute_stage(state.stages, shape, dtype)
        return ServeResponse(
            ok=False, code="rejected", kind=kind, stage=stage,
            error=msg + (f" [{detail}]" if detail else ""),
        )

    # -- submission --------------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None,
               model: Optional[str] = None,
               trace_id: Optional[str] = None) -> PendingResponse:
        """Admit one item. Returns a :class:`PendingResponse` that ALWAYS
        terminates in a structured :class:`ServeResponse` — rejected /
        shed / breaker responses resolve immediately, admitted requests
        resolve when the worker serves (or sheds) them.

        ``trace_id`` joins this request to an existing distributed trace
        (e.g. minted at a :class:`~keystone_tpu.serve.front.FrontClient`);
        when None the admission edge mints one itself iff
        ``KEYSTONE_TRACE_SAMPLE`` selects the request.  Trace ids are pure
        host metadata — they never reach a jitted program."""
        from keystone_tpu.utils import faults

        reg = self._registry()
        model = model or self.default_model
        reg.inc("serve.requests", model=model)
        tid = trace_id if trace_id is not None else maybe_mint()
        try:
            with request_span("serve.admit", tid, model=model):
                # chaos site 1: gateway-internal admission failure — the
                # request still gets a structured response, never a hang
                faults.check("serve.admit")
                if not hasattr(x, "shape"):
                    x = np.asarray(x)
                state = self._nodes_spec.get(model)
                if state is None:
                    return self._finish(_resolved(ServeResponse(
                        ok=False, code="rejected", kind="model",
                        error=f"unknown model {model!r}", model=model,
                        trace_id=tid,
                    )))
                reject = self._admit_issue(x, state)
                if reject is not None:
                    reg.inc("serve.rejected", kind=reject.kind)
                    return self._finish(_resolved(
                        _with_model(reject, model, trace_id=tid)
                    ))
                now = time.monotonic()
                with self._cond:
                    resp = self._gate_locked(state, model, now)
                    if resp is None:
                        req = _Request(
                            x=x, model=model, pending=PendingResponse(),
                            t_submit=now,
                            deadline_t=(now + deadline_ms / 1e3
                                        if deadline_ms is not None else None),
                            probe=(state.breaker == "half_open"
                                   and state.probe_inflight),
                            trace_id=tid,
                        )
                        self._queue.append(req)
                        reg.set_gauge("serve.queue_depth", len(self._queue))
                        self._cond.notify_all()
                if resp is not None:
                    if resp.code == "shed" and self._demote_armed:
                        # queue pressure: cold models are not being asked
                        # for — demote them toward host so the hot model's
                        # dispatches get the HBM. OUTSIDE the condition (the
                        # device->host copies would stall every submit and
                        # the worker); disarmed once a sweep finds no
                        # victims, re-armed when a lookup can re-promote.
                        self._demote_armed = self._demote_cold(model) > 0
                    return self._finish(_resolved(
                        _with_model(resp, model, trace_id=tid)
                    ))
                return req.pending
        except Exception as e:  # injected admit faults and gateway bugs
            logger.warning("admission failed: %s: %s", type(e).__name__, e)
            return self._finish(_resolved(ServeResponse(
                ok=False, code="error",
                error=f"admission failure: {type(e).__name__}: {e}",
                model=model, trace_id=tid,
            )))

    def _gate_locked(self, state: _ModelState, model: str,
                     now: float) -> Optional[ServeResponse]:
        """Breaker + shed decisions (under the lock); None admits."""
        reg = self._registry()
        if self._closing or self._stopped:
            resp = ServeResponse(ok=False, code="shutdown",
                                 error="gateway closed", model=model)
            reg.inc("serve.shed_total", reason="shutdown")
            return resp
        if self.breaker_threshold > 0 and state.breaker != "closed":
            if state.breaker == "open":
                remaining = state.t_open + self.breaker_cooldown_s - now
                if remaining <= 0 and not state.probe_inflight:
                    state.breaker = "half_open"
                    state.probe_inflight = True
                    reg.inc("serve.breaker", event="half_open")
                    reg.set_gauge("serve.breaker_state", 0.5, model=model)
                    logger.warning(
                        "breaker half-open for %s: admitting one probe",
                        model,
                    )
                    return None  # THIS request is the probe
                reg.inc("serve.breaker_fast_fail")
                return ServeResponse(
                    ok=False, code="breaker_open",
                    error="model quarantined (non-finite outputs)",
                    retry_after_s=round(max(remaining, 0.0) or
                                        self.breaker_cooldown_s, 3),
                    model=model,
                )
            # half_open with the probe already in flight: fail fast
            if state.probe_inflight:
                reg.inc("serve.breaker_fast_fail")
                return ServeResponse(
                    ok=False, code="breaker_open",
                    error="half-open probe in flight",
                    retry_after_s=round(self.breaker_cooldown_s, 3),
                    model=model,
                )
            state.probe_inflight = True
            return None
        resp = self._tenant_gate(state, model, now)
        if resp is not None:
            return resp
        depth = len(self._queue)
        over_depth = depth >= self.queue_depth
        over_slo = self._p99_ms > self.slo_ms and depth >= 1
        if over_depth or over_slo:
            reason = "overload"
            reg.inc("serve.shed_total", reason=reason)
            retry_after = max(
                depth * max(self._p50_ms, 1.0) / 1e3, self.slo_ms / 1e3
            )
            return ServeResponse(
                ok=False, code="shed",
                error=("queue full" if over_depth
                       else f"p99 {self._p99_ms:.1f}ms over SLO"),
                retry_after_s=round(retry_after, 3), model=model,
            )
        return None

    def _tenant_gate(self, state: _ModelState, model: str,
                     now: float) -> Optional[ServeResponse]:
        """Per-tenant admission hook (under the lock, after the breaker,
        before the global depth/SLO shed).  The base gateway has no
        per-tenant policy — :class:`keystone_tpu.serve.pool.ModelPool`
        overrides this with the HBM-envelope rejection and the fair-share
        / per-tenant-SLO sheds.  None admits."""
        return None

    def predict(self, x, deadline_ms: Optional[float] = None,
                model: Optional[str] = None, timeout: float = 30.0):
        """Synchronous serve: the value on success, :class:`ServeRejected`
        (carrying the structured response) otherwise."""
        resp = self.submit(x, deadline_ms=deadline_ms,
                           model=model).result(timeout)
        if not resp.ok:
            raise ServeRejected(resp)
        return resp.value

    # -- worker ------------------------------------------------------------

    def start(self) -> None:
        with self._cond:
            if self._worker is not None and self._worker.is_alive():
                return
            self._stopped = False
            self._worker = threading.Thread(
                target=self._run, name="keystone-serve", daemon=True
            )
            self._worker.start()

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the gateway.  ``drain=True`` serves everything already
        admitted first; ``drain=False`` sheds the backlog with structured
        ``shutdown`` responses.  Either way no request is left hanging."""
        with self._cond:
            self._closing = True
            if not drain:
                self._shed_backlog("shutdown")
            self._cond.notify_all()
        worker = self._worker
        if worker is not None and worker.is_alive():
            t0 = time.monotonic()
            while self._queue and time.monotonic() - t0 < timeout:
                time.sleep(0.005)
            with self._cond:
                self._stopped = True
                self._cond.notify_all()
            worker.join(timeout)
        with self._cond:
            self._stopped = True
            self._shed_backlog("shutdown")

    def _shed_backlog(self, code: str) -> None:
        reg = self._registry()
        while self._queue:
            req = self._queue.popleft()
            reg.inc("serve.shed_total", reason=code)
            self._respond(req, ServeResponse(
                ok=False, code=code, error="gateway closed",
                model=req.model,
            ))

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._serve_batch(batch)
            except BaseException as e:  # the no-wedge contract
                logger.warning(
                    "dispatch failed (%s: %s); failing the batch "
                    "structured", type(e).__name__, e,
                )
                for req in batch:
                    self._respond(req, ServeResponse(
                        ok=False, code="error",
                        error=f"dispatch failure: {type(e).__name__}: {e}",
                        model=req.model,
                    ))

    def _collect(self) -> Optional[List[_Request]]:
        """Pop a head-run of same-model requests (up to the ladder max),
        waiting a short coalesce window to batch a burst. None = stop."""
        with self._cond:
            while not self._queue:
                if self._stopped or (self._closing and not self._queue):
                    return None
                self._cond.wait(0.05)
            # coalesce: give a burst one window to land before dispatching
            if (len(self._queue) < self._ladder[-1]
                    and not self._closing and self._coalesce_s > 0):
                self._cond.wait(self._coalesce_s)
            if not self._queue:
                return []
            head_model = self._queue[0].model
            batch: List[_Request] = []
            while (self._queue and len(batch) < self._ladder[-1]
                   and self._queue[0].model == head_model):
                batch.append(self._queue.popleft())
            self._registry().set_gauge(
                "serve.queue_depth", len(self._queue)
            )
            return batch

    def _serve_batch(self, batch: List[_Request]) -> None:
        from keystone_tpu.utils import faults
        from keystone_tpu.utils.retry import call_with_device_retries

        reg = self._registry()
        model = batch[0].model
        now = time.monotonic()
        # deadline filter: drop expired work first, then work that
        # provably cannot meet its deadline at the measured per-shape
        # dispatch estimate for the SURVIVORS' chunk schedule (a batch
        # over the ladder max dispatches as several sequential chunks,
        # and expired entries must not inflate the survivors' estimate)
        alive: List[_Request] = []
        for req in batch:
            if req.deadline_t is not None and now > req.deadline_t:
                reg.inc("serve.shed_total", reason="deadline")
                self._respond(req, ServeResponse(
                    ok=False, code="deadline", error="deadline passed",
                    model=model,
                ))
            else:
                alive.append(req)
        est_s = self._estimate_batch_ms(model, len(alive)) / 1e3
        keep: List[_Request] = []
        for req in alive:
            if req.deadline_t is not None and now + est_s > req.deadline_t:
                reg.inc("serve.shed_total", reason="deadline")
                self._respond(req, ServeResponse(
                    ok=False, code="deadline",
                    error=f"deadline unmeetable (est {est_s * 1e3:.1f}ms)",
                    model=model,
                ))
            else:
                keep.append(req)
        if not keep:
            return
        tids = [r.trace_id for r in keep if r.trace_id is not None]
        btid = tids[0] if tids else None  # batch span joins the 1st trace
        node = self._fetch_model(model)
        # HOST-side batch assembly (numpy), one C-level call: every
        # python-level jax dispatch here is a GIL preemption point, and
        # after a batch response the thundering herd of woken waiters
        # (in-process callers or the front's writer threads) preempted
        # the worker between each of its many small stack/slice/pad
        # dispatches — measured ~45 QPS at p50 44 ms for a 6-row
        # coalesced batch whose actual device program runs in 0.2 ms.
        # numpy stack + pad keep the assembly two C calls; the one
        # jnp.asarray per chunk below is the single transfer, which also
        # makes _jit_apply_batch's donated input buffer genuinely fresh.
        with request_span("serve.coalesce", btid, model=model,
                          batch=len(keep), traced=len(tids)):
            xs = np.stack([np.asarray(r.x) for r in keep])
        self._active_model = model

        def attempt():
            # chaos site 2: the dispatch boundary. Error kinds raise into
            # the retry loop (the production retriable path); a NUMERIC
            # kind poisons the batch — the breaker's sentinel then catches
            # the non-finite outputs downstream (PR-13 semantics).
            spec = faults.check("serve.dispatch")
            b = xs
            if spec is not None:
                b = np.asarray(faults.poison(b, spec.kind))
            outs, i = [], 0
            while i < b.shape[0]:
                n = self._pick_shape(b.shape[0] - i)
                rows = b[i : i + n]  # python slicing clamps at the tail
                if rows.shape[0] < n:
                    chunk = np.zeros((n,) + rows.shape[1:], rows.dtype)
                    chunk[: rows.shape[0]] = rows
                else:
                    chunk = rows
                with request_span("serve.rung", btid, model=model, n=n):
                    outs.append(_jit_apply_batch(node, jnp.asarray(chunk)))
                i += rows.shape[0]
            out = jax.tree_util.tree_map(
                lambda *ls: jnp.concatenate(ls, axis=0)[: xs.shape[0]],
                *outs,
            ) if len(outs) > 1 else jax.tree_util.tree_map(
                lambda l: l[: xs.shape[0]], outs[0]
            )
            flag = _finite_flag(out)
            return jax.block_until_ready((out, flag))

        t0 = time.perf_counter()
        with request_span("serve.dispatch", btid, model=model,
                          batch=len(keep)):
            out, flag = call_with_device_retries(
                attempt, retries=self._retries, backoff_s=self._backoff_s,
                max_backoff_s=1.0, on_retry=self._on_dispatch_retry,
            )
        dt_ms = (time.perf_counter() - t0) * 1e3
        reg.inc("serve.dispatch_total", model=model)
        reg.observe("serve.dispatch_ms", dt_ms)
        self._update_estimate(model, len(keep), dt_ms)
        healthy = bool(flag)
        state = self._nodes_spec[model]
        if not healthy:
            reg.inc("serve.sentinel_trips", model=model)
            self._trip_breaker(state, model, probe=any(
                r.probe for r in keep
            ))
            for req in keep:
                self._respond(req, ServeResponse(
                    ok=False, code="sentinel",
                    error="non-finite output quarantined (health sentinel)",
                    model=model,
                ))
            return
        self._note_healthy(state, model, probe=any(r.probe for r in keep))
        # chaos site 3: the respond boundary — a failure here still
        # terminates every request (structured error, not a hang)
        try:
            faults.check("serve.respond")
        except Exception as e:
            for req in keep:
                self._respond(req, ServeResponse(
                    ok=False, code="error",
                    error=f"respond failure: {type(e).__name__}: {e}",
                    model=model,
                ))
            return
        now = time.monotonic()
        for i, req in enumerate(keep):
            value = jax.tree_util.tree_map(lambda l: l[i], out)
            self._respond(req, ServeResponse(
                ok=True, code="ok", value=value,
                latency_ms=round((now - req.t_submit) * 1e3, 3),
                model=model,
            ))

    # -- breaker -----------------------------------------------------------

    def _trip_breaker(self, state: _ModelState, model: str,
                      probe: bool) -> None:
        reg = self._registry()
        with self._cond:
            state.trips += 1
            if probe:
                state.probe_inflight = False
            if self.breaker_threshold <= 0:
                return
            if probe or (state.breaker == "closed"
                         and state.trips >= self.breaker_threshold):
                state.breaker = "open"
                state.t_open = time.monotonic()
                reg.inc("serve.breaker", event="open")
                reg.set_gauge("serve.breaker_state", 1.0, model=model)
                logger.warning(
                    "breaker OPEN for %s after %d consecutive sentinel "
                    "trip(s)", model, state.trips,
                )

    def _note_healthy(self, state: _ModelState, model: str,
                      probe: bool) -> None:
        reg = self._registry()
        with self._cond:
            state.trips = 0
            # only a PROBE closes an open breaker: a pre-open request that
            # happened to be queued and served healthy must not flap it
            if probe and state.breaker != "closed":
                state.breaker = "closed"
                state.probe_inflight = False
                reg.inc("serve.breaker", event="close")
                reg.set_gauge("serve.breaker_state", 0.0, model=model)
                logger.warning("breaker CLOSED for %s (probe served)", model)

    def breaker_state(self, model: Optional[str] = None) -> str:
        return self._nodes_spec[model or self.default_model].breaker

    # -- degradation -------------------------------------------------------

    def _on_dispatch_retry(self, attempt: int, exc: BaseException) -> None:
        """Pre-retry degradation: the PR-12 OOM hook first (frees the
        ACTIVE intermediate cache's device tier, if one is installed),
        then the gateway's own ladder: demote cold models' device tiers
        and shrink the batch-shape ladder so the retry dispatches a
        smaller program into the HBM the failed attempt could not get."""
        from keystone_tpu.utils.retry import default_on_retry

        default_on_retry(attempt, exc)
        text = str(exc).lower()
        if "resource_exhausted" not in text and "out of memory" not in text:
            return
        reg = self._registry()
        released = self._pool.demote_device_except(
            (self._pool_key(self._active_model or self.default_model),)
        )
        if released:
            reg.inc("serve.model_demotions", released)
        with self._cond:
            if len(self._ladder) > 1:
                self._ladder = self._ladder[:-1]
                reg.inc("serve.degraded")
                reg.set_gauge("serve.ladder_max", self._ladder[-1])
                logger.warning(
                    "OOM under serve: ladder shrunk to %s (attempt %d)",
                    self._ladder, attempt,
                )

    def _demote_cold(self, hot_model: str) -> int:
        released = self._pool.demote_device_except(
            (self._pool_key(hot_model),)
        )
        if released:
            self._registry().inc("serve.model_demotions", released)
        return released

    def _pick_shape(self, n: int) -> int:
        for s in self._ladder:
            if s >= n:
                return s
        return self._ladder[-1]

    # -- stats -------------------------------------------------------------

    def _chunk_shapes(self, n: int) -> List[int]:
        """The ladder rungs ``n`` rows dispatch through — the same chunk
        walk the dispatch loop performs (a batch over the ladder max
        runs as several sequential fixed-shape programs)."""
        shapes: List[int] = []
        i = 0
        while i < n:
            s = self._pick_shape(n - i)
            shapes.append(s)
            i += min(n - i, s)
        return shapes

    def _estimate_ms(self, model: str, shape: int) -> float:
        est = self._est_ms.get((model, shape))
        if est is None:
            vals = [v for (m, _), v in self._est_ms.items() if m == model]
            est = max(vals) if vals else 0.0
        return est

    def _estimate_batch_ms(self, model: str, n: int) -> float:
        """Total dispatch estimate for ``n`` rows: the sum over the
        chunk schedule's per-rung estimates, so deadlines are judged
        against the sequential dispatches they will actually wait for."""
        return sum(
            self._estimate_ms(model, s) for s in self._chunk_shapes(n)
        )

    def _update_estimate(self, model: str, n: int, ms: float) -> None:
        shapes = self._chunk_shapes(n)
        if not shapes:
            return
        per = ms / len(shapes)
        for s in shapes:
            prev = self._est_ms.get((model, s), per)
            self._est_ms[(model, s)] = 0.7 * prev + 0.3 * per

    def _respond(self, req: _Request, resp: ServeResponse) -> None:
        reg = self._registry()
        reg.inc("serve.responses", code=resp.code)
        if req.trace_id is not None and resp.trace_id is None:
            resp = ServeResponse(
                **{**resp.__dict__, "trace_id": req.trace_id}
            )
        with request_span("serve.reply", req.trace_id,
                          model=resp.model, code=resp.code):
            if req.probe and resp.code not in ("ok", "sentinel"):
                # a probe that was shed/errored before its dispatch must
                # free the half-open slot, or the breaker wedges
                # half-open forever
                with self._cond:
                    state = self._nodes_spec.get(req.model)
                    if state is not None:
                        state.probe_inflight = False
            if resp.ok:
                now = time.monotonic()
                self._done.append((now, resp.latency_ms))
                reg.observe("serve.latency_ms", resp.latency_ms,
                            buckets=LATENCY_BUCKETS_MS, model=resp.model)
                # recompute the windowed percentiles at most every 16
                # responses / 0.5 s: a full filter+sort of the 512-entry
                # window per served request would tax the dispatch worker
                # at exactly the QPS the gauges are meant to measure
                self._lat_pending += 1
                if (self._lat_pending >= 16
                        or now - self._lat_refreshed >= 0.5):
                    self._refresh_latency(now)
            req.pending._resolve(resp)

    def _refresh_latency(self, now: float) -> None:
        self._lat_pending = 0
        self._lat_refreshed = now
        window = [l for t, l in self._done if now - t <= 5.0]
        if not window:
            return
        window.sort()
        self._p50_ms = window[len(window) // 2]
        self._p99_ms = window[min(len(window) - 1, int(0.99 * len(window)))]
        reg = self._registry()
        reg.set_gauge("serve.qps", round(len(window) / 5.0, 3))
        reg.set_gauge("serve.p50_ms", round(self._p50_ms, 3))
        reg.set_gauge("serve.p99_ms", round(self._p99_ms, 3))

    def _finish(self, pending: PendingResponse) -> PendingResponse:
        resp = pending._response
        if resp is not None:
            self._registry().inc("serve.responses", code=resp.code)
        return pending

    @staticmethod
    def _registry():
        from keystone_tpu.telemetry import get_registry

        return get_registry()

    def stats(self) -> dict:
        """Queryable gateway state (mirrors the serve.* telemetry)."""
        reg = self._registry()
        with self._cond:
            return {
                "qps": reg.get_gauge("serve.qps") or 0.0,
                "p50_ms": round(self._p50_ms, 3),
                "p99_ms": round(self._p99_ms, 3),
                "slo_ms": self.slo_ms,
                "queue_depth": len(self._queue),
                "queue_bound": self.queue_depth,
                "ladder": list(self._ladder),
                "shed_total": int(
                    reg.counter_family_total("serve.shed_total")
                ),
                "degraded": int(reg.counter_family_total("serve.degraded")),
                "breakers": {
                    name: st.breaker
                    for name, st in self._nodes_spec.items()
                },
            }

    def compile_cache_size(self) -> int:
        """Size of the shared dispatch compile cache — constant across
        steady-state serving (the zero-recompile pin)."""
        return _jit_apply_batch._cache_size()


# ---------------------------------------------------------------------------
# construction helpers
# ---------------------------------------------------------------------------


def _dispatchable(pipe):
    """(dispatch node, stage graph) for a servable pipeline: Cacher
    markers are stripped (they are bulk-path materialization hints; the
    serve program is ONE fused dispatch), host nodes are rejected — a
    gateway serves compiled fixed-shape programs only."""
    from keystone_tpu.analysis.contracts import stage_list
    from keystone_tpu.core.pipeline import DAG, Chain, Node

    if not isinstance(pipe, Node):
        raise TypeError(
            f"serve() needs a pipeline Node, got {type(pipe).__name__}"
        )
    stages, _ = stage_list(pipe)
    for node, _deps in stages:
        if not getattr(node, "jittable", True):
            raise TypeError(
                f"serve(): stage {type(node).__name__} is a host node — "
                "the gateway dispatches compiled fixed-shape programs only "
                "(run host stages offline, serve the jittable suffix)"
            )
    if isinstance(pipe, DAG):
        return pipe, stages
    if len(stages) == 1:
        return stages[0][0], stages
    return Chain(stages=tuple(n for n, _ in stages)), stages


def _resolve_item_spec(item_spec, stages):
    """The per-item abstract input: explicit ``item_spec`` (shape without
    the batch axis, or a ShapeDtypeStruct) wins; otherwise the earliest
    stage declaring an ``in_template`` contract provides it."""
    from keystone_tpu.analysis import contracts

    if item_spec is not None:
        if hasattr(item_spec, "shape") and hasattr(item_spec, "dtype"):
            return jax.ShapeDtypeStruct(
                tuple(item_spec.shape), np.dtype(item_spec.dtype)
            )
        raise TypeError(
            "item_spec must carry shape+dtype (e.g. jax.ShapeDtypeStruct)"
        )
    for node, _deps in stages:
        contract = contracts.contract_of(node)
        if contract is not None and contract.in_template is not None:
            try:
                template = contract.in_template()
            except Exception:
                continue
            leaf = contracts.leading_leaf(template)
            if leaf is not None and leaf.shape:
                # templates carry a leading item axis of 1
                return jax.ShapeDtypeStruct(
                    tuple(leaf.shape[1:]), np.dtype(leaf.dtype)
                )
    raise ValueError(
        "serve() could not derive the item spec: no stage declares an "
        "in_template contract — pass item_spec=jax.ShapeDtypeStruct(...)"
    )


def _attribute_stage(stages, item_shape, dtype) -> Tuple[Optional[str], str]:
    """Run the SHARED contract propagation with the bad request's aval and
    name the first stage that fails — the admission rejection carries the
    same attribution a `keystone-tpu check` pass would report."""
    from keystone_tpu.analysis import contracts

    try:
        aval = jax.ShapeDtypeStruct(
            (1,) + tuple(item_shape), np.dtype(dtype or np.float32)
        )
        records = contracts.propagate(stages, aval)
        for r in records:
            if r.issue is not None:
                return r.name, r.issue.message
    except Exception:
        pass
    return None, ""


def _with_model(resp: ServeResponse, model: str,
                trace_id: Optional[str] = None) -> ServeResponse:
    fields = {**resp.__dict__, "model": model}
    if trace_id is not None and fields.get("trace_id") is None:
        fields["trace_id"] = trace_id
    return ServeResponse(**fields)


def serve(pipe, item_spec=None, **kwargs) -> Gateway:
    """Build a :class:`Gateway` over a fitted pipeline (module docstring).

    ``item_spec`` is the per-item abstract input (shape WITHOUT the batch
    axis + dtype); omitted, it is derived from the earliest stage's
    declared ``in_template`` contract.  Keyword knobs (each also an env
    knob, explicit argument winning): ``shapes`` / ``KEYSTONE_SERVE_SHAPES``
    (the fixed micro-batch ladder), ``slo_ms`` / ``KEYSTONE_SERVE_SLO_MS``,
    ``queue_depth`` / ``KEYSTONE_SERVE_QUEUE_DEPTH``,
    ``breaker_threshold`` / ``KEYSTONE_SERVE_BREAKER`` (0 disables the
    breaker).  ``start=False`` builds the gateway paused (tests/smokes
    queue deterministic bursts, then :meth:`Gateway.start`)."""
    return Gateway(pipe, item_spec, **kwargs)
