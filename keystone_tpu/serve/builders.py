"""Named, deterministic model builders for fleet replica workers.

A replica is a fresh OS process (``serve/fleet.py --worker``); it cannot
be handed a fitted pipeline object, so it is handed a BUILDER NAME and
reconstructs the model itself.  Every builder here is seeded and
deterministic: N replicas built from the same name serve bit-identical
models, which is what makes the fleet smoke's coalesced-batch parity check
(front output vs a locally built twin) meaningful.

``resolve`` also accepts ``"module:attr"`` for builders living outside
this registry (the same spec convention the ingest worker pool uses for
its decode hooks).
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ModelSpec", "BUILDERS", "resolve", "build"]


@dataclass(frozen=True)
class ModelSpec:
    """One tenant: a fitted pipeline + its per-item input spec and the
    per-tenant pool kwargs (:meth:`ModelPool.add_model`)."""

    name: str
    pipe: Any
    item_spec: Any
    slo_ms: Optional[float] = None
    priority: int = 0


def _cosine_chain(dim: int, feats: int, seed: int):
    import jax
    import jax.numpy as jnp

    from keystone_tpu.core.pipeline import chain
    from keystone_tpu.ops.stats import CosineRandomFeatures, LinearRectifier

    node = chain(
        CosineRandomFeatures.create(
            dim, feats, 0.1, jax.random.key(seed)
        ),
        LinearRectifier(max_val=0.0),
    )
    spec = jax.ShapeDtypeStruct((dim,), jnp.float32)
    return node, spec


def cosine() -> List[ModelSpec]:
    """One tenant, MXU-shaped enough to measure: a cosine random-feature
    chain (the same family the ``serve.dispatch`` IR audit lowers).  No
    fitting — replicas build it in milliseconds."""
    node, spec = _cosine_chain(dim=64, feats=512, seed=17)
    return [ModelSpec(name="default", pipe=node, item_spec=spec)]


def two_tenant() -> List[ModelSpec]:
    """Two tenants with distinct chains and widths: 'hot' (the flood
    tenant in fairness tests) and 'cold' (the one fairness protects)."""
    hot, hot_spec = _cosine_chain(dim=24, feats=96, seed=3)
    cold, cold_spec = _cosine_chain(dim=16, feats=64, seed=5)
    return [
        ModelSpec(name="hot", pipe=hot, item_spec=hot_spec),
        ModelSpec(name="cold", pipe=cold, item_spec=cold_spec),
    ]


BUILDERS: Dict[str, Callable[[], List[ModelSpec]]] = {
    "cosine": cosine,
    "two_tenant": two_tenant,
}


def resolve(name: str) -> Callable[[], List[ModelSpec]]:
    """Builder by registry name, or ``module:attr`` for external ones."""
    if name in BUILDERS:
        return BUILDERS[name]
    if ":" in name:
        mod, _, attr = name.partition(":")
        return getattr(importlib.import_module(mod), attr)
    raise KeyError(
        f"unknown builder {name!r}: registry has {sorted(BUILDERS)}, or "
        "pass 'module:attr'"
    )


def build(name: str) -> List[ModelSpec]:
    specs = resolve(name)()
    if not specs:
        raise ValueError(f"builder {name!r} produced no models")
    return list(specs)
