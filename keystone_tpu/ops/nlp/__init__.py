"""NLP nodes: string preprocessing, n-grams, vocab encoding, language models.

Reference package: ``src/main/scala/nodes/nlp/`` (see SURVEY.md §2.6).
"""

from keystone_tpu.ops.nlp.strings import Tokenizer, Trim, LowerCase
from keystone_tpu.ops.nlp.ngrams import (
    NGram,
    NGramsFeaturizer,
    NGramsCounts,
    NGramsCountsMode,
    encoded_ngrams,
)
from keystone_tpu.ops.nlp.indexers import (
    BackoffIndexer,
    NaiveBitPackIndexer,
    NGramIndexerImpl,
    PackedNGramIndexer,
)
from keystone_tpu.ops.nlp.word_frequency import (
    WordFrequencyEncoder,
    WordFrequencyTransformer,
    OOV,
)
from keystone_tpu.ops.nlp.stupid_backoff import (
    StupidBackoffEstimator,
    StupidBackoffModel,
)
from keystone_tpu.ops.nlp.corenlp import CoreNLPFeatureExtractor, lemmatize
from keystone_tpu.ops.nlp.fast_text import (
    EncodedCommonSparseFeatures,
    EncodedNGramVectorizer,
)

__all__ = [
    "Tokenizer",
    "Trim",
    "LowerCase",
    "NGram",
    "NGramsFeaturizer",
    "NGramsCounts",
    "NGramsCountsMode",
    "encoded_ngrams",
    "BackoffIndexer",
    "NaiveBitPackIndexer",
    "NGramIndexerImpl",
    "PackedNGramIndexer",
    "WordFrequencyEncoder",
    "WordFrequencyTransformer",
    "OOV",
    "StupidBackoffEstimator",
    "StupidBackoffModel",
    "CoreNLPFeatureExtractor",
    "lemmatize",
    "EncodedCommonSparseFeatures",
    "EncodedNGramVectorizer",
]
