"""Word-frequency vocabulary encoding.

Reference: ``nodes/nlp/WordFrequencyEncoder.scala:8-63`` — fit a vocabulary
ordered by descending corpus frequency (most frequent word -> id 0), broadcast
the word->id map, encode documents with OOV -> -1, and expose per-id unigram
counts (consumed by ``StupidBackoffEstimator``).

This node is the host/device frontier of the NLP stack: strings in, dense
int32 id tensors out. Downstream n-gram counting and language-model scoring
operate purely on the encoded tensors.
"""

from __future__ import annotations

import collections
from typing import ClassVar, Dict, List, Sequence, Tuple

import flax.struct as struct
import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer

OOV = -1


class WordFrequencyTransformer(Transformer):
    """Encode token sequences with a fitted frequency-ranked vocabulary."""

    jittable: ClassVar[bool] = False
    word_index: Dict[str, int] = struct.field(pytree_node=False)
    unigram_counts: Dict[int, int] = struct.field(pytree_node=False)

    @property
    def vocab_size(self) -> int:
        return len(self.word_index)

    def apply(self, tokens: Sequence[str]) -> List[int]:
        wi = self.word_index
        return [wi.get(t, OOV) for t in tokens]

    def apply_batch(self, docs: Sequence[Sequence[str]]) -> List[List[int]]:
        return [self.apply(d) for d in docs]

    def encode_padded(
        self, docs: Sequence[Sequence[str]]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Encode to a padded int32 ``[num_docs, max_len]`` batch (+ lengths),
        the tensor layout the device-side n-gram ops consume."""
        encoded = self.apply_batch(docs)
        lengths = np.array([len(e) for e in encoded], dtype=np.int32)
        max_len = max(1, int(lengths.max(initial=0)))
        ids = np.full((len(encoded), max_len), OOV, dtype=np.int32)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
        return ids, lengths


class WordFrequencyEncoder(Estimator):
    """Fit the frequency-ranked vocabulary (``WordFrequencyEncoder.scala:13-30``)."""

    def fit(self, docs: Sequence[Sequence[str]]) -> WordFrequencyTransformer:
        counts: collections.Counter = collections.Counter()
        for doc in docs:
            counts.update(doc)
        # Descending count; ties broken by first-seen order like a stable sort.
        ranked = sorted(counts.items(), key=lambda kv: -kv[1])
        word_index = {w: i for i, (w, _) in enumerate(ranked)}
        unigram_counts = {i: c for i, (_, c) in enumerate(ranked)}
        return WordFrequencyTransformer(
            word_index=word_index, unigram_counts=unigram_counts
        )
