"""Stupid Backoff language model (Brants et al. 2007).

Reference: ``nodes/nlp/StupidBackoff.scala`` —

- ``InitialBigramPartitioner`` (``StupidBackoff.scala:25-57``) partitions
  n-grams by their first two context words so each partition can score its
  n-grams against a *local* hash map (``scoreLocally``, ``:60-92``).
- ``StupidBackoffEstimator.fit`` (``:155-180``): ``reduceByKey`` with that
  partitioner, then per-partition recursive scoring; the model serves
  ``score(ngram)`` via ``RDD.lookup`` (``:104-117``).

TPU-native redesign — no partitioner, no shuffle, no per-partition maps:

- Counts for each order live in one **sorted int64 packed-key table** (a pair
  of arrays) built host-side with ``np.unique`` and shipped to device.
- Scoring a batch of n-grams is a single XLA program: pack suffixes of every
  backoff level with bit shifts, binary-search each level's table
  (``jnp.searchsorted`` — O(log N) per query on sorted keys), and fold the
  backoff recursion bottom-up with ``jnp.where``:

      S_1(w)        = count(w) / num_tokens
      S_k(suffix_k) = count_k > 0 ? count_k / count(context)
                                  : alpha * S_{k-1}(suffix_{k-1})

  The data-locality trick the reference builds from a custom partitioner
  (co-locating an n-gram with its backoff contexts) is free here: every level
  of the recursion is just another vectorized gather on device.
"""

from __future__ import annotations

import functools
from typing import ClassVar, Dict, List, Optional, Sequence, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.ops.nlp.indexers import PackedNGramIndexer

DEFAULT_ALPHA = 0.4


@functools.partial(jax.jit, static_argnums=(2, 3))
def _score_batch_device(
    model: "StupidBackoffModel", ngrams: jnp.ndarray, order: int, word_bits: int
) -> jnp.ndarray:
    """Score ``[B, order]`` id n-grams; one fused XLA program per (order, shapes).

    Must run under ``jax.experimental.enable_x64`` so the int64 packed keys
    survive tracing (jax's default 32-bit mode would silently truncate any
    vocab × order combination wider than 31 bits).
    """
    b = ngrams.shape[0]
    total = jnp.maximum(model.num_tokens, 1.0)

    def lookup(keys: jnp.ndarray, valid: jnp.ndarray, k: int):
        """Count of each order-k packed key (0 where absent/invalid)."""
        if k == 1:
            ids = jnp.clip(keys, 0, model.unigram_counts.shape[0] - 1).astype(jnp.int32)
            c = model.unigram_counts[ids]
        else:
            tk = model.table_keys[k - 2]
            tc = model.table_counts[k - 2]
            if tk.shape[0] == 0:
                return jnp.zeros_like(keys, dtype=jnp.float32)
            pos = jnp.searchsorted(tk, keys)
            pos = jnp.clip(pos, 0, tk.shape[0] - 1)
            c = jnp.where(tk[pos] == keys, tc[pos], 0.0)
        return jnp.where(valid, c, 0.0)

    def pack_suffix(k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Packed key of the last-k-word suffix + validity (no OOV ids)."""
        suffix = ngrams[:, order - k :]
        valid = jnp.all(suffix >= 0, axis=1)
        key = suffix[:, 0].astype(jnp.int64)
        for i in range(1, k):
            key = (key << word_bits) | jnp.where(
                suffix[:, i] >= 0, suffix[:, i], 0
            ).astype(jnp.int64)
        return key, valid

    # Bottom-up backoff fold.
    uni_keys, uni_valid = pack_suffix(1)
    score = lookup(uni_keys, uni_valid, 1) / total
    for k in range(2, order + 1):
        keys, valid = pack_suffix(k)
        c = lookup(keys, valid, k)
        # context of the k-suffix = its first k-1 words = drop current word.
        ctx_keys = keys >> word_bits
        ctx = lookup(ctx_keys, valid, k - 1)
        hit = (c > 0) & (ctx > 0)
        score = jnp.where(hit, c / jnp.maximum(ctx, 1.0), model.alpha * score)
    return score.reshape((b,))


class StupidBackoffModel(Transformer):
    """Fitted LM: per-order sorted count tables, device-batch scoring.

    When ``host_tables`` is set (vocab × order too wide for 63-bit packed
    keys), scoring runs the identical recursion on host dict lookups instead
    — the :class:`NGramIndexerImpl`-style tuple-keyed path.
    """

    jittable: ClassVar[bool] = False

    # table_keys[i] / table_counts[i] hold order-(i+2) n-grams.
    table_keys: Tuple[jnp.ndarray, ...]
    table_counts: Tuple[jnp.ndarray, ...]
    unigram_counts: jnp.ndarray  # dense [vocab] float32
    num_tokens: jnp.ndarray  # scalar float32
    alpha: float = struct.field(pytree_node=False, default=DEFAULT_ALPHA)
    word_bits: int = struct.field(pytree_node=False, default=20)
    max_order: int = struct.field(pytree_node=False, default=3)
    # order -> {id_tuple: count}; None on the packed/device path.
    host_tables: Optional[Tuple[Dict[Tuple[int, ...], float], ...]] = struct.field(
        pytree_node=False, default=None
    )

    def _score_batch_host(self, ngrams: np.ndarray) -> np.ndarray:
        """Tuple-keyed host recursion — same math as the device fold."""
        total = max(float(self.num_tokens), 1.0)
        uni = np.asarray(self.unigram_counts)

        def count(ng: Tuple[int, ...]) -> float:
            if any(w < 0 for w in ng):
                return 0.0
            if len(ng) == 1:
                return float(uni[ng[0]]) if ng[0] < uni.shape[0] else 0.0
            table = self.host_tables[len(ng) - 2]
            return table.get(ng, 0.0)

        out = np.zeros(ngrams.shape[0], np.float32)
        for i, row in enumerate(ngrams):
            ng = tuple(int(w) for w in row)
            score = count(ng[-1:]) / total
            for k in range(2, len(ng) + 1):
                c = count(ng[-k:])
                ctx = count(ng[-k:-1])
                score = c / ctx if (c > 0 and ctx > 0) else self.alpha * score
            out[i] = score
        return out

    @property
    def vocab_size(self) -> int:
        return int(self.unigram_counts.shape[0])

    def score_batch(self, ngrams: np.ndarray) -> np.ndarray:
        """Score a ``[B, order]`` batch of id n-grams (pad/OOV id = -1)."""
        ngrams = np.asarray(ngrams, dtype=np.int32)
        if ngrams.ndim != 2:
            raise ValueError("score_batch expects [B, order]")
        order = ngrams.shape[1]
        if not 1 <= order <= self.max_order:
            raise ValueError(f"order must be 1..{self.max_order}")
        if self.host_tables is not None:
            return self._score_batch_host(ngrams)
        with jax.enable_x64():
            return np.asarray(
                _score_batch_device(self, jnp.asarray(ngrams), order, self.word_bits)
            )

    def apply(self, ngram: Sequence[int]) -> float:
        """Single-item serving path (the reference's ``RDD.lookup`` analog)."""
        return float(self.score_batch(np.asarray([ngram]))[0])

    def apply_batch(self, ngrams) -> np.ndarray:
        return self.score_batch(np.asarray(ngrams))

    def scores_arrays(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Score every trained n-gram, as per-order arrays.

        Returns ``[(ngrams int32 [N, order], scores float32 [N]), ...]`` in
        ascending order, each sorted by packed key — the allocation-free form
        of :meth:`scores` (no per-n-gram Python tuples)."""
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        if self.host_tables is not None:
            for table in self.host_tables:
                if not table:
                    continue
                ngrams = np.array(sorted(table), dtype=np.int64)
                s = self._score_batch_host(ngrams)
                out.append((ngrams.astype(np.int32), s))
            return out
        for i, keys in enumerate(self.table_keys):
            order = i + 2
            keys_np = np.asarray(keys)
            if keys_np.size == 0:
                continue
            ngrams = np.zeros((keys_np.size, order), dtype=np.int32)
            rest = keys_np.copy()
            for j in range(order - 1, -1, -1):
                ngrams[:, j] = (rest & ((1 << self.word_bits) - 1)).astype(np.int32)
                rest >>= self.word_bits
            out.append((ngrams, self.score_batch(ngrams)))
        return out

    def scores(self) -> List[Tuple[Tuple[int, ...], float]]:
        """Score every trained n-gram (the reference's ``scoresRDD``)."""
        out: List[Tuple[Tuple[int, ...], float]] = []
        for ngrams, s in self.scores_arrays():
            out.extend((tuple(map(int, ng)), float(v)) for ng, v in zip(ngrams, s))
        return out


class StupidBackoffEstimator:
    """Build the count tables from n-gram counts + unigram counts.

    Reference: ``StupidBackoff.scala:96-180``. ``unigram_counts`` is keyed by
    encoded word id (the output of ``WordFrequencyEncoder``); ``fit`` takes
    ``[(id_tuple, count)]`` pairs for orders >= 2 (the output of
    ``NGramsCounts`` over encoded docs). Duplicate n-grams (e.g. NoAdd-mode
    partials) are summed here.
    """

    def __init__(self, unigram_counts: Dict[int, int], alpha: float = DEFAULT_ALPHA):
        self.unigram_counts = dict(unigram_counts)
        self.alpha = float(alpha)

    def fit(self, ngram_counts: Sequence[Tuple[Tuple[int, ...], int]]) -> StupidBackoffModel:
        vocab_size = (max(self.unigram_counts) + 1) if self.unigram_counts else 1
        max_order = max((len(ng) for ng, _ in ngram_counts), default=2)

        by_order: Dict[int, List[Tuple[Tuple[int, ...], int]]] = {}
        for ng, c in ngram_counts:
            if any(w < 0 for w in ng):
                continue  # OOV-containing n-grams are unscorable
            by_order.setdefault(len(ng), []).append((ng, c))

        uni = np.zeros((vocab_size,), dtype=np.float32)
        for wid, c in self.unigram_counts.items():
            if wid >= 0:
                uni[wid] = c

        try:
            indexer = PackedNGramIndexer(vocab_size, max_order)
        except ValueError:
            # vocab × order too wide for 63-bit keys: host tuple-dict tables
            # (the NGramIndexerImpl-style path; device scoring disabled).
            host_tables = []
            for order in range(2, max_order + 1):
                table: Dict[Tuple[int, ...], float] = {}
                for ng, c in by_order.get(order, []):
                    table[tuple(ng)] = table.get(tuple(ng), 0.0) + float(c)
                host_tables.append(table)
            return StupidBackoffModel(
                table_keys=(),
                table_counts=(),
                unigram_counts=uni,
                num_tokens=np.float32(uni.sum()),
                alpha=self.alpha,
                word_bits=0,
                max_order=max_order,
                host_tables=tuple(host_tables),
            )

        table_keys: List[jnp.ndarray] = []
        table_counts: List[jnp.ndarray] = []
        for order in range(2, max_order + 1):
            entries = by_order.get(order, [])
            if entries:
                arr = np.array([ng for ng, _ in entries], dtype=np.int64)
                keys = indexer.pack_batch(arr)
                counts = np.array([c for _, c in entries], dtype=np.float64)
                # merge duplicates, sort by key: the host reduceByKey, run by
                # the native multithreaded aggregator (numpy fallback inside).
                from keystone_tpu.native.ngram import count_by_key

                uniq, summed = count_by_key(keys, counts)
                # Tables stay host-side numpy so int64 keys reach the device
                # intact (they are converted under enable_x64 at trace time).
                table_keys.append(uniq)
                table_counts.append(summed.astype(np.float32))
            else:
                table_keys.append(np.zeros((0,), dtype=np.int64))
                table_counts.append(np.zeros((0,), dtype=np.float32))

        return StupidBackoffModel(
            table_keys=tuple(table_keys),
            table_counts=tuple(table_counts),
            unigram_counts=uni,
            num_tokens=np.float32(uni.sum()),
            alpha=self.alpha,
            word_bits=indexer.word_bits,
            max_order=max_order,
        )

    def fit_encoded(
        self, ids: np.ndarray, lengths: np.ndarray, orders: Sequence[int]
    ) -> StupidBackoffModel:
        """Vectorized fit from a padded encoded batch — no per-n-gram tuples.

        ``ids``/``lengths`` are ``WordFrequencyTransformer.encode_padded``
        output; windows come from :func:`~keystone_tpu.ops.nlp.ngrams.encoded_ngrams`,
        keys from :class:`PackedNGramIndexer`, aggregation from the native
        ``count_by_key``. Produces the same tables as
        ``fit(NGramsCounts()(NGramsFeaturizer(orders)(encoded)))`` —
        equivalence pinned in ``tests/test_nlp.py``. OOV-containing windows
        (id < 0) are dropped, like ``fit``. Falls back to the tuple path when
        vocab × order overflows 63-bit packing.
        """
        from keystone_tpu.native.ngram import count_by_key
        from keystone_tpu.ops.nlp.ngrams import encoded_ngrams

        orders = sorted(o for o in set(orders) if o >= 2)
        vocab_size = (max(self.unigram_counts) + 1) if self.unigram_counts else 1
        # Windows per order, pre-OOV-filter: fit() derives max_order from
        # the n-grams present (incl. OOV-containing ones, which it drops
        # only afterwards), so the data — not the request — sets the model's
        # order here too (exact-equivalence contract with fit()).
        raw_grams = {o: encoded_ngrams(ids, lengths, o) for o in orders}
        max_order = max(
            (o for o, g in raw_grams.items() if g.shape[0]), default=2
        )
        try:
            indexer = PackedNGramIndexer(vocab_size, max_order)
        except ValueError:
            # hand fit() the UNfiltered windows: it drops OOV-containing
            # n-grams itself but derives max_order before doing so, and the
            # two paths must agree on that (exact-equivalence contract)
            counts: List[Tuple[Tuple[int, ...], int]] = []
            for o in orders:
                counts.extend((tuple(map(int, g)), 1) for g in raw_grams[o])
            return self.fit(counts)

        uni = np.zeros((vocab_size,), dtype=np.float32)
        for wid, c in self.unigram_counts.items():
            if wid >= 0:
                uni[wid] = c

        table_keys: List[np.ndarray] = []
        table_counts: List[np.ndarray] = []
        for order in range(2, max_order + 1):
            grams = raw_grams.get(order, np.zeros((0, order), np.int32))
            grams = grams[(grams >= 0).all(axis=1)]
            if grams.shape[0]:
                uniq, summed = count_by_key(indexer.pack_batch(grams))
                table_keys.append(uniq)
                table_counts.append(summed.astype(np.float32))
            else:
                table_keys.append(np.zeros((0,), dtype=np.int64))
                table_counts.append(np.zeros((0,), dtype=np.float32))

        return StupidBackoffModel(
            table_keys=tuple(table_keys),
            table_counts=tuple(table_counts),
            unigram_counts=uni,
            num_tokens=np.float32(uni.sum()),
            alpha=self.alpha,
            word_bits=indexer.word_bits,
            max_order=max_order,
        )
