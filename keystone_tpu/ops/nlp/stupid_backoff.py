"""Stupid Backoff language model (Brants et al. 2007).

Reference: ``nodes/nlp/StupidBackoff.scala`` —

- ``InitialBigramPartitioner`` (``StupidBackoff.scala:25-57``) partitions
  n-grams by their first two context words so each partition can score its
  n-grams against a *local* hash map (``scoreLocally``, ``:60-92``).
- ``StupidBackoffEstimator.fit`` (``:155-180``): ``reduceByKey`` with that
  partitioner, then per-partition recursive scoring; the model serves
  ``score(ngram)`` via ``RDD.lookup`` (``:104-117``).

TPU-native redesign — no partitioner, no shuffle, no per-partition maps:

- Counts for each order live in one **sorted int64 packed-key table** (a pair
  of arrays) built host-side with ``np.unique`` and shipped to device.
- Scoring a batch of n-grams is a single XLA program: pack suffixes of every
  backoff level with bit shifts, binary-search each level's table
  (``jnp.searchsorted`` — O(log N) per query on sorted keys), and fold the
  backoff recursion bottom-up with ``jnp.where``:

      S_1(w)        = count(w) / num_tokens
      S_k(suffix_k) = count_k > 0 ? count_k / count(context)
                                  : alpha * S_{k-1}(suffix_{k-1})

  The data-locality trick the reference builds from a custom partitioner
  (co-locating an n-gram with its backoff contexts) is free here: every level
  of the recursion is just another vectorized gather on device.
"""

from __future__ import annotations

import functools
from typing import ClassVar, Dict, List, Optional, Sequence, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.ops.nlp.indexers import PackedNGramIndexer

DEFAULT_ALPHA = 0.4


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _fit_tables_device(
    ids: jnp.ndarray,
    lengths: jnp.ndarray,
    orders: Tuple[int, ...],
    word_bits: int,
    vocab_size: int,
    uni: Optional[jnp.ndarray] = None,
):
    """Count every requested order's n-grams + unigrams in one XLA program.

    Returns ``(uni [vocab] f32, table_keys tuple, table_counts tuple,
    sizes [n_tables] i32)`` with one (sentinel-padded) table per order in
    ``2..max(orders)`` — orders not requested get empty tables, matching
    ``fit_encoded``'s layout. ``uni`` overrides the unigram table (the
    estimator's encoder-provided counts, which may come from a different
    corpus than the n-gram batch — the ``fit``/``fit_encoded`` contract);
    when None it is counted from ``ids`` itself.
    """
    from keystone_tpu.ops.nlp.device_count import (
        count_ngrams_device,
        unigram_table_device,
    )

    if uni is None:
        uni = unigram_table_device(ids, vocab_size, lengths)
    table_keys, table_counts, sizes = [], [], []
    for order in range(2, max(orders) + 1):
        if order in orders:
            uniq, counts, n = count_ngrams_device(ids, lengths, order, word_bits)
        else:
            uniq = jnp.zeros((0,), jnp.int64)
            counts = jnp.zeros((0,), jnp.float32)
            n = jnp.int32(0)
        table_keys.append(uniq)
        table_counts.append(counts)
        sizes.append(n)
    return uni, tuple(table_keys), tuple(table_counts), jnp.stack(sizes)


def _fit_tables_sharded(
    ids: jnp.ndarray,
    lengths: jnp.ndarray,
    orders: Tuple[int, ...],
    word_bits: int,
    vocab_size: int,
    uni: Optional[jnp.ndarray],
    mesh,
    axis: str,
    capacity: Optional[int] = None,
):
    """:func:`_fit_tables_device` across a document-sharded mesh — the
    cluster-wide ``reduceByKey`` (``StupidBackoff.scala:156-159``): per-shard
    sort+segment combine, all-gather of the compacted per-shard tables over
    ICI, one merge reduce (design note in ``device_count.py``). The doc axis
    is padded to the mesh axis size with empty documents (length 0 — no
    valid windows, no effect on any count). Returns the extra ``overflowed``
    flag (nonzero only when ``capacity`` undersizes some shard's distinct
    count; the caller raises)."""
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.ops.nlp.device_count import (
        _compact_gather_merge,
        pad_docs_to_mesh,
        sum_by_key,
        unigram_table_device,
        window_keys,
    )

    p = mesh.shape[axis]
    ids, lengths = pad_docs_to_mesh(
        jnp.asarray(ids), jnp.asarray(lengths), p
    )
    d, max_len = ids.shape

    def caps(order):
        n_local = (d // p) * max(0, max_len - order + 1)
        return n_local if capacity is None else min(int(capacity), n_local)

    # ONE shard_map body — unigrams + every order's count + exchange in a
    # single XLA program per the _fit_tables_device design (the padded ids
    # are read once; XLA schedules the per-order ICI exchanges together).
    # Encoder-provided unigram counts (uni) never enter the manual region —
    # they are data about a possibly different corpus, passed through.
    count_uni = uni is None

    def shard_fn(ids_l, len_l):
        keys_out, counts_out, sizes_out = [], [], []
        overflowed = jnp.int32(0)
        for order in range(2, max(orders) + 1):
            if order in orders and max_len - order + 1 > 0:
                k_l, v_l = window_keys(ids_l, len_l, order, word_bits)
                uniq, tot, nu, over = _compact_gather_merge(
                    *sum_by_key(k_l, v_l), caps(order), axis
                )
                overflowed = jnp.maximum(overflowed, over)
            else:
                # empty-table dtype matches the single-device fit exactly
                # (dtype drives _table_lookup's method choice): a skipped
                # order is int64 (_fit_tables_device), while a requested
                # order with no valid windows (max_len < order) follows
                # window_keys' packing rule
                if order in orders:
                    dt = jnp.int32 if order * word_bits <= 30 else jnp.int64
                else:
                    dt = jnp.int64
                uniq = jnp.zeros((0,), dt)
                tot = jnp.zeros((0,), jnp.float32)
                nu = jnp.int32(0)
            keys_out.append(uniq)
            counts_out.append(tot)
            sizes_out.append(nu)
        out = (
            tuple(keys_out), tuple(counts_out),
            jnp.stack(sizes_out), overflowed,
        )
        if count_uni:
            uni_out = jax.lax.psum(
                unigram_table_device(ids_l, vocab_size, len_l), axis
            )
            return (uni_out,) + out
        return out

    rep = P()
    sharded = P(axis)
    n_tables = max(orders) - 1
    table_specs = ((rep,) * n_tables, (rep,) * n_tables, rep, rep)
    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        check_vma=False,  # outputs are deterministic fns of all-gathered /
                          # psum'd (hence replicated) data
        in_specs=(sharded, sharded),
        out_specs=((rep,) + table_specs) if count_uni else table_specs,
    )
    result = fn(ids, lengths)
    if count_uni:
        return result
    return (uni,) + result


def _table_lookup(model: "StupidBackoffModel", qk: jnp.ndarray, k: int) -> jnp.ndarray:
    """Count of each order-``k`` packed query key (0 where absent).

    Casts queries to the table's own dtype (fit may keep tables int32 when
    the packed width allows — value-preserving for any order-k suffix) and
    picks the searchsorted algorithm by dtype: the co-sorting ``sort`` method
    is ~19x faster than the binary-search ``scan`` on TPU for int32 keys but
    ~4x *slower* for int64 (measured, v5e).
    """
    if k == 1:
        ids = jnp.clip(qk, 0, model.unigram_counts.shape[0] - 1).astype(jnp.int32)
        return model.unigram_counts[ids]
    tk = model.table_keys[k - 2]
    tc = model.table_counts[k - 2]
    if tk.shape[0] == 0:
        return jnp.zeros(qk.shape, jnp.float32)
    qk = qk.astype(tk.dtype)
    method = "sort" if tk.dtype == jnp.int32 else "scan"
    pos = jnp.clip(jnp.searchsorted(tk, qk, method=method), 0, tk.shape[0] - 1)
    return jnp.where(tk[pos] == qk, tc[pos], 0.0)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _score_table_device(
    model: "StupidBackoffModel", i: int, word_bits: int
) -> jnp.ndarray:
    """Score table ``i``'s own keys (order ``i+2``) — the ``scoresRDD`` path.

    Exploits self-alignment: the top level's count *is* the table's own count
    column (no lookup), so an order-2 table scores with zero binary searches
    (its context counts are the dense unigram array) and an order-k table
    needs searches only for levels 2..k-1 and the top context.
    """
    order = i + 2
    keys = model.table_keys[i]
    total = jnp.maximum(model.num_tokens, 1.0)

    def suffix(k: int) -> jnp.ndarray:
        return keys & jnp.asarray((1 << (k * word_bits)) - 1, keys.dtype)

    score = _table_lookup(model, suffix(1), 1) / total
    for k in range(2, order):
        sk = suffix(k)
        c = _table_lookup(model, sk, k)
        ctx = _table_lookup(model, sk >> word_bits, k - 1)
        hit = (c > 0) & (ctx > 0)
        score = jnp.where(hit, c / jnp.maximum(ctx, 1.0), model.alpha * score)
    c = model.table_counts[i]  # own counts: trained keys are their own hits
    ctx = _table_lookup(model, keys >> word_bits, order - 1)
    hit = (c > 0) & (ctx > 0)
    return jnp.where(hit, c / jnp.maximum(ctx, 1.0), model.alpha * score)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _score_batch_device(
    model: "StupidBackoffModel", ngrams: jnp.ndarray, order: int, word_bits: int
) -> jnp.ndarray:
    """Score ``[B, order]`` id n-grams; one fused XLA program per (order, shapes).

    Must run under ``jax.experimental.enable_x64`` so int64 packed keys
    survive tracing (jax's default 32-bit mode would silently truncate any
    vocab × order combination wider than 31 bits). Invalid n-grams (any
    id < 0) score through the masked fold: every level containing the OOV
    word misses its table and takes the backoff branch.
    """
    b = ngrams.shape[0]
    dt = jnp.int32 if order * word_bits <= 30 else jnp.int64

    # Pack the full n-gram once; per-level masks carve out suffixes. An OOV
    # id packs as 0 but its level is forced onto the backoff branch below.
    key = jnp.where(ngrams[:, 0] >= 0, ngrams[:, 0], 0).astype(dt)
    for i in range(1, order):
        key = (key << word_bits) | jnp.where(
            ngrams[:, i] >= 0, ngrams[:, i], 0
        ).astype(dt)

    total = jnp.maximum(model.num_tokens, 1.0)

    def lookup(qk: jnp.ndarray, valid: jnp.ndarray, k: int):
        return jnp.where(valid, _table_lookup(model, qk, k), 0.0)

    def suffix(k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        sk = key & jnp.asarray((1 << (k * word_bits)) - 1, dt) if k < order else key
        valid = jnp.all(ngrams[:, order - k :] >= 0, axis=1)
        return sk, valid

    uni_keys, uni_valid = suffix(1)
    score = lookup(uni_keys, uni_valid, 1) / total
    for k in range(2, order + 1):
        sk, valid = suffix(k)
        c = lookup(sk, valid, k)
        ctx = lookup(sk >> word_bits, valid, k - 1)
        hit = (c > 0) & (ctx > 0)
        score = jnp.where(hit, c / jnp.maximum(ctx, 1.0), model.alpha * score)
    return score.reshape((b,))


class StupidBackoffModel(Transformer):
    """Fitted LM: per-order sorted count tables, device-batch scoring.

    When ``host_tables`` is set (vocab × order too wide for 63-bit packed
    keys), scoring runs the identical recursion on host dict lookups instead
    — the :class:`NGramIndexerImpl`-style tuple-keyed path.

    Tables built on device (:meth:`StupidBackoffEstimator.fit_device`) are
    **sentinel-padded** to a static length (``device_count.SENTINEL`` keys
    with count 0 behind the real entries); ``table_sizes`` records the true
    entry counts. Padding is invisible to lookups — a sentinel slot can never
    equal a real query key.
    """

    jittable: ClassVar[bool] = False

    # table_keys[i] / table_counts[i] hold order-(i+2) n-grams.
    table_keys: Tuple[jnp.ndarray, ...]
    table_counts: Tuple[jnp.ndarray, ...]
    unigram_counts: jnp.ndarray  # dense [vocab] float32
    num_tokens: jnp.ndarray  # scalar float32
    alpha: float = struct.field(pytree_node=False, default=DEFAULT_ALPHA)
    word_bits: int = struct.field(pytree_node=False, default=20)
    max_order: int = struct.field(pytree_node=False, default=3)
    # order -> {id_tuple: count}; None on the packed/device path.
    host_tables: Optional[Tuple[Dict[Tuple[int, ...], float], ...]] = struct.field(
        pytree_node=False, default=None
    )
    # true entry count per table when sentinel-padded (device fit); None
    # means every table is exact-size (host fit) OR sizes live on device
    # only (``table_sizes_dev`` below, the trim=False fit).
    table_sizes: Optional[Tuple[int, ...]] = struct.field(
        pytree_node=False, default=None
    )
    # device-resident true sizes ([n_tables] int32) for trim=False fits —
    # no host sync happened; host-materializing APIs pull it on demand and
    # latency-critical consumers fold it into their one batched fetch.
    table_sizes_dev: Optional[jnp.ndarray] = None

    def _score_batch_host(self, ngrams: np.ndarray) -> np.ndarray:
        """Tuple-keyed host recursion — same math as the device fold."""
        total = max(float(self.num_tokens), 1.0)
        uni = np.asarray(self.unigram_counts)

        def count(ng: Tuple[int, ...]) -> float:
            if any(w < 0 for w in ng):
                return 0.0
            if len(ng) == 1:
                return float(uni[ng[0]]) if ng[0] < uni.shape[0] else 0.0
            table = self.host_tables[len(ng) - 2]
            return table.get(ng, 0.0)

        out = np.zeros(ngrams.shape[0], np.float32)
        for i, row in enumerate(ngrams):
            ng = tuple(int(w) for w in row)
            score = count(ng[-1:]) / total
            for k in range(2, len(ng) + 1):
                c = count(ng[-k:])
                ctx = count(ng[-k:-1])
                score = c / ctx if (c > 0 and ctx > 0) else self.alpha * score
            out[i] = score
        return out

    @property
    def vocab_size(self) -> int:
        return int(self.unigram_counts.shape[0])

    def score_batch(self, ngrams: np.ndarray) -> np.ndarray:
        """Score a ``[B, order]`` batch of id n-grams (pad/OOV id = -1)."""
        ngrams = np.asarray(ngrams, dtype=np.int32)
        if ngrams.ndim != 2:
            raise ValueError("score_batch expects [B, order]")
        order = ngrams.shape[1]
        if not 1 <= order <= self.max_order:
            raise ValueError(f"order must be 1..{self.max_order}")
        if self.host_tables is not None:
            return self._score_batch_host(ngrams)
        with jax.enable_x64():
            return np.asarray(
                _score_batch_device(self, jnp.asarray(ngrams), order, self.word_bits)
            )

    def apply(self, ngram: Sequence[int]) -> float:
        """Single-item serving path (the reference's ``RDD.lookup`` analog)."""
        return float(self.score_batch(np.asarray([ngram]))[0])

    def apply_batch(self, ngrams) -> np.ndarray:
        return self.score_batch(np.asarray(ngrams))

    def scores_device(self) -> List[Tuple[jnp.ndarray, jnp.ndarray, int]]:
        """Score every trained n-gram without leaving the device.

        Returns ``[(order, keys [N], scores float32 [N], true_size), ...]``
        per non-empty order >= 2 — keys stay packed (scoring operates on them
        directly, :func:`_score_table_device`) and arrays stay on device.
        ``true_size`` is a python int for trimmed/host-fit models and a
        device scalar (no sync) for trim=False fits, where the tables carry
        sentinel padding and rows past ``true_size`` are meaningless —
        consumers fold the scalar into their own fetch. The reference's
        ``scoresRDD`` without the collect.
        """
        if self.host_tables is not None:
            raise ValueError("scores_device requires packed (device) tables")
        out = []
        with jax.enable_x64():
            for i, keys in enumerate(self.table_keys):
                if keys.shape[0] == 0:
                    continue
                if self.table_sizes is not None:
                    size = self.table_sizes[i]
                elif self.table_sizes_dev is not None:
                    size = self.table_sizes_dev[i]
                else:
                    size = int(keys.shape[0])
                s = _score_table_device(self, i, self.word_bits)
                out.append((i + 2, jnp.asarray(keys), s, size))
        return out

    def scores_arrays(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Score every trained n-gram, as per-order arrays.

        Returns ``[(ngrams int32 [N, order], scores float32 [N]), ...]`` in
        ascending order, each sorted by packed key — the allocation-free form
        of :meth:`scores` (no per-n-gram Python tuples)."""
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        if self.host_tables is not None:
            for table in self.host_tables:
                if not table:
                    continue
                ngrams = np.array(sorted(table), dtype=np.int64)
                s = self._score_batch_host(ngrams)
                out.append((ngrams.astype(np.int32), s))
            return out
        sizes = self.table_sizes
        if sizes is None and self.table_sizes_dev is not None:
            # trim=False fit: the sizes never crossed to the host — this
            # host-materializing API pulls them now (one sync)
            sizes = tuple(int(n) for n in np.asarray(self.table_sizes_dev))
        for i, keys in enumerate(self.table_keys):
            order = i + 2
            keys_np = np.asarray(keys)
            if sizes is not None:
                keys_np = keys_np[: sizes[i]]
            if keys_np.size == 0:
                continue
            ngrams = np.zeros((keys_np.size, order), dtype=np.int32)
            rest = keys_np.copy()
            for j in range(order - 1, -1, -1):
                ngrams[:, j] = (rest & ((1 << self.word_bits) - 1)).astype(np.int32)
                rest >>= self.word_bits
            out.append((ngrams, self.score_batch(ngrams)))
        return out

    def scores(self) -> List[Tuple[Tuple[int, ...], float]]:
        """Score every trained n-gram (the reference's ``scoresRDD``)."""
        out: List[Tuple[Tuple[int, ...], float]] = []
        for ngrams, s in self.scores_arrays():
            out.extend((tuple(map(int, ng)), float(v)) for ng, v in zip(ngrams, s))
        return out


class StupidBackoffEstimator:
    """Build the count tables from n-gram counts + unigram counts.

    Reference: ``StupidBackoff.scala:96-180``. ``unigram_counts`` is keyed by
    encoded word id (the output of ``WordFrequencyEncoder``); ``fit`` takes
    ``[(id_tuple, count)]`` pairs for orders >= 2 (the output of
    ``NGramsCounts`` over encoded docs). Duplicate n-grams (e.g. NoAdd-mode
    partials) are summed here.
    """

    def __init__(self, unigram_counts: Dict[int, int], alpha: float = DEFAULT_ALPHA):
        self.unigram_counts = dict(unigram_counts)
        self.alpha = float(alpha)

    def fit(self, ngram_counts: Sequence[Tuple[Tuple[int, ...], int]]) -> StupidBackoffModel:
        vocab_size = (max(self.unigram_counts) + 1) if self.unigram_counts else 1
        max_order = max((len(ng) for ng, _ in ngram_counts), default=2)

        by_order: Dict[int, List[Tuple[Tuple[int, ...], int]]] = {}
        for ng, c in ngram_counts:
            if any(w < 0 for w in ng):
                continue  # OOV-containing n-grams are unscorable
            by_order.setdefault(len(ng), []).append((ng, c))

        uni = np.zeros((vocab_size,), dtype=np.float32)
        for wid, c in self.unigram_counts.items():
            if wid >= 0:
                uni[wid] = c

        try:
            indexer = PackedNGramIndexer(vocab_size, max_order)
        except ValueError:
            # vocab × order too wide for 63-bit keys: host tuple-dict tables
            # (the NGramIndexerImpl-style path; device scoring disabled).
            host_tables = []
            for order in range(2, max_order + 1):
                table: Dict[Tuple[int, ...], float] = {}
                for ng, c in by_order.get(order, []):
                    table[tuple(ng)] = table.get(tuple(ng), 0.0) + float(c)
                host_tables.append(table)
            return StupidBackoffModel(
                table_keys=(),
                table_counts=(),
                unigram_counts=uni,
                num_tokens=np.float32(uni.sum()),
                alpha=self.alpha,
                word_bits=0,
                max_order=max_order,
                host_tables=tuple(host_tables),
            )

        table_keys: List[jnp.ndarray] = []
        table_counts: List[jnp.ndarray] = []
        for order in range(2, max_order + 1):
            entries = by_order.get(order, [])
            if entries:
                arr = np.array([ng for ng, _ in entries], dtype=np.int64)
                keys = indexer.pack_batch(arr)
                counts = np.array([c for _, c in entries], dtype=np.float64)
                # merge duplicates, sort by key: the host reduceByKey, run by
                # the native multithreaded aggregator (numpy fallback inside).
                from keystone_tpu.native.ngram import count_by_key

                uniq, summed = count_by_key(keys, counts)
                # Tables stay host-side numpy so int64 keys reach the device
                # intact (they are converted under enable_x64 at trace time).
                table_keys.append(uniq)
                table_counts.append(summed.astype(np.float32))
            else:
                table_keys.append(np.zeros((0,), dtype=np.int64))
                table_counts.append(np.zeros((0,), dtype=np.float32))

        return StupidBackoffModel(
            table_keys=tuple(table_keys),
            table_counts=tuple(table_counts),
            unigram_counts=uni,
            num_tokens=np.float32(uni.sum()),
            alpha=self.alpha,
            word_bits=indexer.word_bits,
            max_order=max_order,
        )

    def fit_device(
        self,
        ids,
        lengths,
        orders: Sequence[int],
        vocab_size: Optional[int] = None,
        trim: bool = True,
        mesh=None,
        mesh_axis: str = "data",
        shard_capacity: Optional[int] = None,
    ) -> StupidBackoffModel:
        """Fit entirely on device: counting is sort + segment-reduce on chip.

        The device analog of :meth:`fit_encoded` (same tables up to sentinel
        padding — pinned in ``tests/test_nlp.py``): window packing, n-gram
        counting (``device_count.count_ngrams_device``), and unigram counts
        all run as one XLA program over the padded id batch; nothing but the
        true table sizes (a few scalars) ever returns to the host. The
        reference pays this as a ``reduceByKey`` shuffle over executor hash
        maps (``StupidBackoff.scala:156-159``, ``ngrams.scala:150-183``).

        One contract difference from ``fit``/``fit_encoded``, stated: the
        model's ``max_order`` is ``max(orders)`` as *requested* (a static
        property of the compiled program), not re-derived from which orders
        happen to be present in the data. Raises ``ValueError`` when
        vocab × order overflows 63-bit packing (no silent host fallback —
        callers choose their fallback path).

        ``trim=False`` skips the fit's only host sync (the table-size pull
        that enables static trimming): tables stay sentinel-padded, the true
        sizes stay on device (``table_sizes_dev``), and lookups binary-search
        the padded length. Worth it only for int32-packable configs
        (``max_order * word_bits <= 30``), where padded searches ride the
        fast ``sort`` method; int64 corpora pay the ~4x-slower ``scan`` over
        ~6x-longer tables — keep the default there. The latency-critical
        pipeline path uses this to run fit-to-score with a SINGLE host round
        trip; serve-oriented callers should keep the default (smaller
        resident tables, per-fit static shapes).

        ``mesh`` (with >1 device on ``mesh_axis``) runs the cluster-wide
        counting path (``_fit_tables_sharded``): documents row-sharded over
        the mesh, per-shard combine, compacted-table all-gather + merge —
        the reference's ``reduceByKey`` shuffle as dense ICI collectives.
        Tables come out identical to the single-device fit (pinned in
        ``tests/test_sharded_count.py``). ``shard_capacity`` caps the
        per-shard compacted table (traffic ∝ capacity); an undersized cap
        raises rather than undercounting.
        """
        orders = tuple(sorted(o for o in set(orders) if o >= 2))
        if not orders:
            raise ValueError("fit_device needs at least one order >= 2")
        max_order = max(orders)
        if vocab_size is None:
            if not self.unigram_counts:
                # defaulting to 1 would set word_bits=1 and silently mis-pack
                # every real id — fail loudly instead
                raise ValueError(
                    "fit_device needs vocab_size when no unigram_counts are "
                    "present (cannot infer the id range)"
                )
            vocab_size = max(self.unigram_counts) + 1
        indexer = PackedNGramIndexer(vocab_size, max_order)
        uni_in = None
        if self.unigram_counts:
            # honor the encoder-provided counts (they may come from a
            # different corpus than this n-gram batch — fit_encoded contract)
            uni_np = np.zeros((int(vocab_size),), np.float32)
            for wid, c in self.unigram_counts.items():
                if wid >= 0:
                    uni_np[wid] = c
            uni_in = jnp.asarray(uni_np)
        with jax.enable_x64():
            if mesh is not None and mesh.shape[mesh_axis] > 1:
                uni, keys, counts, sizes, over = _fit_tables_sharded(
                    jnp.asarray(ids),
                    jnp.asarray(lengths),
                    orders,
                    indexer.word_bits,
                    int(vocab_size),
                    uni_in,
                    mesh,
                    mesh_axis,
                    shard_capacity,
                )
                from keystone_tpu.ops.nlp.device_count import (
                    check_shard_capacity,
                )

                check_shard_capacity(over, shard_capacity)
            else:
                uni, keys, counts, sizes = _fit_tables_device(
                    jnp.asarray(ids),
                    jnp.asarray(lengths),
                    orders,
                    indexer.word_bits,
                    int(vocab_size),
                    uni_in,
                )
            table_sizes = None
            sizes_dev = None if trim else sizes
            if trim:
                table_sizes = tuple(int(s) for s in np.asarray(sizes))
                # the size pull is the fit's one host sync; once sizes are
                # known (static), trim the sentinel padding with static
                # slices so every later lookup binary-searches the true
                # table, not the padded window count (~6x smaller tables at
                # Zipf-corpus scales)
                keys = tuple(k[:n] for k, n in zip(keys, table_sizes))
                counts = tuple(c[:n] for c, n in zip(counts, table_sizes))
        return StupidBackoffModel(
            table_keys=keys,
            table_counts=counts,
            unigram_counts=uni,
            num_tokens=uni.sum(),
            alpha=self.alpha,
            word_bits=indexer.word_bits,
            max_order=max_order,
            table_sizes=table_sizes,
            table_sizes_dev=sizes_dev,
        )

    def fit_encoded(
        self, ids: np.ndarray, lengths: np.ndarray, orders: Sequence[int]
    ) -> StupidBackoffModel:
        """Vectorized fit from a padded encoded batch — no per-n-gram tuples.

        ``ids``/``lengths`` are ``WordFrequencyTransformer.encode_padded``
        output; windows come from :func:`~keystone_tpu.ops.nlp.ngrams.encoded_ngrams`,
        keys from :class:`PackedNGramIndexer`, aggregation from the native
        ``count_by_key``. Produces the same tables as
        ``fit(NGramsCounts()(NGramsFeaturizer(orders)(encoded)))`` —
        equivalence pinned in ``tests/test_nlp.py``. OOV-containing windows
        (id < 0) are dropped, like ``fit``. Falls back to the tuple path when
        vocab × order overflows 63-bit packing.
        """
        from keystone_tpu.native.ngram import count_by_key
        from keystone_tpu.ops.nlp.ngrams import encoded_ngrams

        orders = sorted(o for o in set(orders) if o >= 2)
        vocab_size = (max(self.unigram_counts) + 1) if self.unigram_counts else 1
        # Windows per order, pre-OOV-filter: fit() derives max_order from
        # the n-grams present (incl. OOV-containing ones, which it drops
        # only afterwards), so the data — not the request — sets the model's
        # order here too (exact-equivalence contract with fit()).
        raw_grams = {o: encoded_ngrams(ids, lengths, o) for o in orders}
        max_order = max(
            (o for o, g in raw_grams.items() if g.shape[0]), default=2
        )
        try:
            indexer = PackedNGramIndexer(vocab_size, max_order)
        except ValueError:
            # hand fit() the UNfiltered windows: it drops OOV-containing
            # n-grams itself but derives max_order before doing so, and the
            # two paths must agree on that (exact-equivalence contract)
            counts: List[Tuple[Tuple[int, ...], int]] = []
            for o in orders:
                counts.extend((tuple(map(int, g)), 1) for g in raw_grams[o])
            return self.fit(counts)

        uni = np.zeros((vocab_size,), dtype=np.float32)
        for wid, c in self.unigram_counts.items():
            if wid >= 0:
                uni[wid] = c

        table_keys: List[np.ndarray] = []
        table_counts: List[np.ndarray] = []
        for order in range(2, max_order + 1):
            grams = raw_grams.get(order, np.zeros((0, order), np.int32))
            grams = grams[(grams >= 0).all(axis=1)]
            if grams.shape[0]:
                uniq, summed = count_by_key(indexer.pack_batch(grams))
                table_keys.append(uniq)
                table_counts.append(summed.astype(np.float32))
            else:
                table_keys.append(np.zeros((0,), dtype=np.int64))
                table_counts.append(np.zeros((0,), dtype=np.float32))

        return StupidBackoffModel(
            table_keys=tuple(table_keys),
            table_counts=tuple(table_counts),
            unigram_counts=uni,
            num_tokens=np.float32(uni.sum()),
            alpha=self.alpha,
            word_bits=indexer.word_bits,
            max_order=max_order,
        )
