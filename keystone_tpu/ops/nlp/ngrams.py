"""N-gram featurization and counting.

Reference: ``nodes/nlp/ngrams.scala`` —

- ``NGramsFeaturizer[T]`` (``ngrams.scala:18-89``): for each token sequence,
  emit all n-grams of every order in ``orders`` (consecutive orders, e.g.
  1..2).
- ``NGram[T]`` (``ngrams.scala:98-129``): hashable n-gram wrapper. Python
  tuples already hash/compare by value, so the wrapper here is just ``tuple``.
- ``NGramsCounts[T]`` (``ngrams.scala:150-183``): count n-grams. ``Default``
  mode sums counts across partitions (``reduceByKey`` + sort by descending
  count); ``NoAdd`` keeps per-partition counts un-merged. On a TPU mesh there
  is no partitioner to preserve, so ``NoAdd`` simply skips the global sort —
  both modes produce exact global counts from one host hash-aggregation.

Token-level n-gram work is host-side (tuples of words). The TPU path is the
*encoded* one: :class:`~keystone_tpu.ops.nlp.word_frequency.WordFrequencyEncoder`
maps words to dense int32 ids, after which n-gram formation, packing, and
counting are integer-tensor programs (see ``indexers.py`` / ``stupid_backoff.py``).
"""

from __future__ import annotations

import collections
from enum import Enum
from typing import ClassVar, List, Sequence, Tuple

import flax.struct as struct
import numpy as np

from keystone_tpu.core.pipeline import FunctionNode, Transformer

NGram = tuple  # value-hashable n-gram (ngrams.scala:98-129)


class NGramsFeaturizer(Transformer):
    """All n-grams of consecutive orders per token sequence.

    ``NGramsFeaturizer(1 to 2)(docs)`` → per doc, every unigram then every
    bigram, in sequence order (``ngrams.scala:56-79``).
    """

    jittable: ClassVar[bool] = False
    orders: Tuple[int, ...] = struct.field(pytree_node=False, default=(1, 2))

    def __post_init__(self):
        orders = tuple(self.orders)
        if not orders or min(orders) < 1:
            raise ValueError(f"orders must be >= 1, got {orders}")

    def apply(self, tokens: Sequence) -> List[tuple]:
        out: List[tuple] = []
        n_tokens = len(tokens)
        for order in self.orders:
            for i in range(n_tokens - order + 1):
                out.append(tuple(tokens[i : i + order]))
        return out

    def apply_batch(self, docs: Sequence[Sequence]) -> List[List[tuple]]:
        return [self.apply(d) for d in docs]


class NGramsCountsMode(Enum):
    DEFAULT = "default"  # global counts, sorted by descending count
    NO_ADD = "noadd"  # global counts, unsorted (reference: no cross-partition add)


class NGramsCounts(FunctionNode):
    """Count n-grams across the whole corpus.

    Reference ``ngrams.scala:150-183``: per-partition ``JHashMap`` counting,
    then ``reduceByKey`` (+ ``sortBy(-count)``) in Default mode. Here one host
    pass builds exact global counts; Default additionally sorts by descending
    count like the reference.

    Input: list of per-doc n-gram lists (output of :class:`NGramsFeaturizer`).
    Output: list of ``(ngram, count)`` pairs.
    """

    jittable: ClassVar[bool] = False
    mode: NGramsCountsMode = struct.field(
        pytree_node=False, default=NGramsCountsMode.DEFAULT
    )

    def apply_batch(self, docs: Sequence[Sequence[tuple]]) -> List[Tuple[tuple, int]]:
        counts: collections.Counter = collections.Counter()
        for doc in docs:
            counts.update(doc)
        items = list(counts.items())
        if self.mode is NGramsCountsMode.DEFAULT:
            items.sort(key=lambda kv: -kv[1])
        return items


def encoded_ngrams(ids: np.ndarray, lengths: np.ndarray, order: int) -> np.ndarray:
    """Vectorized n-gram formation over an encoded, padded token batch.

    ``ids``: int32 ``[num_docs, max_len]`` word ids (pad = -1);
    ``lengths``: ``[num_docs]`` true lengths. Returns all ``order``-grams as an
    int32 ``[total, order]`` array — the tensorized analog of
    ``NGramsFeaturizer`` for the post-encoding (device) path.
    """
    ids = np.asarray(ids)
    n_docs, max_len = ids.shape
    if max_len < order:
        return np.zeros((0, order), dtype=np.int32)
    # Sliding windows over each row: [n_docs, max_len - order + 1, order]
    windows = np.stack([ids[:, i : max_len - order + 1 + i] for i in range(order)], -1)
    pos = np.arange(max_len - order + 1)[None, :]
    valid = pos + order <= np.asarray(lengths)[:, None]
    return windows[valid].astype(np.int32)
