"""Lemmatizing + entity-substituting n-gram featurizer.

Reference: ``nodes/nlp/CoreNLPFeatureExtractor.scala:18-45`` — tokenize,
lemmatize, and NER-tag text with the external "sista processors" CoreNLP
stack, substitute entity class tokens for recognized entities, then emit
n-grams.

That external NLP stack has no place in a TPU framework image, so this node
reproduces the *pipeline behavior* (token -> lemma -> entity-substituted
n-grams) with a dependency-free rule engine:

- tokenization: word/number regex with raw-text sentence boundaries;
- lemmatization: an English suffix stripper with a ~150-form irregular
  table, doubled-consonant undoubling, and Porter-style ``e`` restoration —
  intentionally lightweight, still not a tagger-driven lemmatizer;
- entity substitution: consecutive capitalized mid-sentence tokens merge
  into ONE typed entity token — ``<PERSON>``/``<LOCATION>``/
  ``<ORGANIZATION>`` via small gazetteers/suffix cues, ``<ENT>`` otherwise —
  and numerals become ``<DATE>`` (years, months, weekdays) or ``<NUM>``,
  mirroring how the reference substitutes CoreNLP's entity-class strings
  for recognized mentions (``CoreNLPFeatureExtractor.scala:27-41``).

Still a stand-in, and labeled as such (README "Known capability gaps"): no
statistical tagging, no coreference, gazetteer-bounded recall. The node is
host-side; its output feeds the same TermFrequency / CommonSparseFeatures
path as the plain tokenizer.
"""

from __future__ import annotations

import functools
import re
from typing import ClassVar, List, Sequence, Tuple

import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.ops.nlp.ngrams import NGramsFeaturizer

_TOKEN_RE = re.compile(r"[A-Za-z]+|[0-9]+(?:\.[0-9]+)?")


@functools.lru_cache(maxsize=None)
def _featurizer(orders: Tuple[int, ...]) -> NGramsFeaturizer:
    # one immutable featurizer per orders tuple, not one per document
    return NGramsFeaturizer(orders=orders)

_IRREGULAR = {
    "is": "be", "are": "be", "was": "be", "were": "be", "been": "be", "am": "be",
    "has": "have", "had": "have", "having": "have",
    "does": "do", "did": "do", "done": "do", "doing": "do",
    "went": "go", "gone": "go", "goes": "go",
    "said": "say", "says": "say",
    "made": "make", "making": "make",
    "took": "take", "taken": "take", "taking": "take",
    "saw": "see", "seen": "see", "got": "get", "gotten": "get",
    "came": "come", "coming": "come", "knew": "know", "known": "know",
    "thought": "think", "found": "find", "gave": "give", "given": "give",
    "giving": "give", "told": "tell", "became": "become", "left": "leave",
    "felt": "feel", "brought": "bring", "began": "begin", "begun": "begin",
    "kept": "keep", "held": "hold", "wrote": "write", "written": "write",
    "writing": "write", "stood": "stand", "heard": "hear", "meant": "mean",
    "met": "meet", "ran": "run", "running": "run", "paid": "pay",
    "sat": "sit", "spoke": "speak", "spoken": "speak", "led": "lead",
    "grew": "grow", "grown": "grow", "lost": "lose", "losing": "lose",
    "fell": "fall", "fallen": "fall", "sent": "send", "built": "build",
    "understood": "understand", "drew": "draw", "drawn": "draw",
    "broke": "break", "broken": "break", "spent": "spend", "rose": "rise",
    "risen": "rise", "drove": "drive", "driven": "drive", "bought": "buy",
    "wore": "wear", "worn": "wear", "chose": "choose", "chosen": "choose",
    "ate": "eat", "eaten": "eat", "won": "win", "taught": "teach",
    "caught": "catch", "sold": "sell", "fought": "fight", "sought": "seek",
    "slept": "sleep", "threw": "throw", "thrown": "throw", "shown": "show",
    "using": "use", "used": "use",
    "men": "man", "women": "woman", "children": "child",
    "mice": "mouse", "feet": "foot", "teeth": "tooth", "people": "person",
    "geese": "goose", "oxen": "ox", "lives": "life", "wives": "wife",
    "knives": "knife", "leaves": "leaf", "selves": "self",
    "halves": "half", "shelves": "shelf", "wolves": "wolf",
    "better": "good", "best": "good", "worse": "bad", "worst": "bad",
}

_VOWELS = set("aeiou")


def _cvc(stem: str) -> bool:
    """Porter's *o: consonant-vowel-consonant ending, last not w/x/y —
    the shape where the base form ends in silent e (mak+e, lov+e)."""
    if len(stem) < 3:
        return False
    c2, v, c1 = stem[-3], stem[-2], stem[-1]
    return (
        c1 not in _VOWELS and c1 not in "wxy"
        and v in _VOWELS
        and c2 not in _VOWELS
    )


def _strip_participle(w: str, suffix: str) -> str:
    stem = w[: -len(suffix)]
    if len(stem) > 2 and stem[-1] == stem[-2] and stem[-1] not in "lsz":
        return stem[:-1]  # running -> run, stopped -> stop (keep fall, miss)
    if stem.endswith(("at", "bl", "iz")) or _cvc(stem):
        return stem + "e"  # locating -> locate, loved -> love, making -> make
    return stem


def lemmatize(word: str) -> str:
    """Rule-based English lemmatizer (lowercased input)."""
    w = word.lower()
    if w in _IRREGULAR:
        return _IRREGULAR[w]
    n = len(w)
    if n > 4 and w.endswith("ies"):
        return w[:-3] + "y"
    if n > 4 and w.endswith(("sses", "ches", "shes", "xes", "zes")):
        return w[:-2]
    if n > 3 and w.endswith("s") and not w.endswith(("ss", "us", "is")):
        return w[:-1]
    if n > 5 and w.endswith("ing"):
        return _strip_participle(w, "ing")
    if n > 4 and w.endswith("ed"):
        return _strip_participle(w, "ed")
    if n > 4 and w.endswith("ly"):
        return w[:-2]
    return w


# Gazetteers for typed entity substitution — deliberately small; anything
# capitalized mid-sentence that matches nothing stays <ENT>.
_MONTHS = {
    "january", "february", "march", "april", "may", "june", "july",
    "august", "september", "october", "november", "december",
}
_WEEKDAYS = {
    "monday", "tuesday", "wednesday", "thursday", "friday", "saturday",
    "sunday",
}
_FIRST_NAMES = {
    "john", "mary", "james", "robert", "michael", "william", "david",
    "richard", "joseph", "thomas", "charles", "margaret", "sarah", "karen",
    "nancy", "lisa", "barbara", "elizabeth", "jennifer", "maria", "susan",
    "george", "paul", "peter", "mark", "steven", "andrew", "kenneth",
    "alice", "anna", "emma", "henry", "jack", "samuel", "daniel",
}
_LOCATIONS = {
    "america", "england", "france", "germany", "china", "japan", "india",
    "russia", "canada", "australia", "brazil", "mexico", "italy", "spain",
    "egypt", "israel", "turkey", "iran", "iraq", "korea", "vietnam",
    "london", "paris", "berlin", "moscow", "tokyo", "beijing", "boston",
    "chicago", "seattle", "houston", "dallas", "atlanta", "denver",
    "washington", "california", "texas", "florida", "ohio", "virginia",
    "europe", "asia", "africa", "arctic", "antarctica",
}
_ORG_CUES = {
    "inc", "corp", "ltd", "co", "company", "university", "institute",
    "college", "bank", "committee", "association", "department", "agency",
    "council", "bureau", "commission", "ministry", "society", "union",
}


def _entity_type(run: List[str]) -> str:
    """Type a run of consecutive capitalized tokens (one entity mention)."""
    lower = [t.lower() for t in run]
    if any(t in _ORG_CUES for t in lower):
        return "<ORGANIZATION>"
    if any(t in _LOCATIONS for t in lower):
        return "<LOCATION>"
    if lower[0] in _FIRST_NAMES:
        return "<PERSON>"
    return "<ENT>"


class CoreNLPFeatureExtractor(Transformer):
    """Text -> entity-substituted lemma n-grams (orders ``orders``)."""

    jittable: ClassVar[bool] = False
    orders: Tuple[int, ...] = struct.field(pytree_node=False, default=(1, 2))

    def apply(self, text: str) -> List[tuple]:
        tokens: List[str] = []
        cap_run: List[str] = []  # consecutive capitalized tokens = 1 mention
        sentence_start = True
        prev_end = 0

        def flush_run():
            if cap_run:
                tokens.append(_entity_type(cap_run))
                cap_run.clear()

        for m in _TOKEN_RE.finditer(text):
            # sentence boundary lives in the raw text between tokens
            # ("bark. The" -> '. ' separates), not in the token itself
            gap = text[prev_end : m.start()]
            # line breaks end sentences/mentions too: headline- and
            # list-style text carries no terminal punctuation
            if any(ch in ".!?\n" for ch in gap):
                sentence_start = True
            if cap_run and (gap.strip() or "\n" in gap):
                flush_run()  # punctuation/comma/newline ends a mention
            tok = m.group(0)
            low = tok.lower()
            if tok[0].isdigit():
                flush_run()
                if len(tok) == 4 and tok.isdigit() and 1000 <= int(tok) <= 2999:
                    tokens.append("<DATE>")  # year
                else:
                    tokens.append("<NUM>")
            elif tok[0].isupper() and (low in _MONTHS or low in _WEEKDAYS):
                # capitalization required: lowercase 'may'/'march'/'sat' are
                # (modal/motion/sit) verbs, not dates
                flush_run()
                tokens.append("<DATE>")
            elif tok[0].isupper() and not sentence_start:
                cap_run.append(tok)
            else:
                flush_run()
                tokens.append(lemmatize(tok))
            sentence_start = False
            prev_end = m.end()
        flush_run()
        return _featurizer(tuple(self.orders)).apply(tokens)

    def apply_batch(self, texts: Sequence[str]) -> List[List[tuple]]:
        return [self.apply(t) for t in texts]
