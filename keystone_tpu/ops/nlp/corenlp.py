"""Lemmatizing + entity-substituting n-gram featurizer.

Reference: ``nodes/nlp/CoreNLPFeatureExtractor.scala:18-45`` — tokenize,
lemmatize, and NER-tag text with the external "sista processors" CoreNLP
stack, substitute entity class tokens for recognized entities, then emit
n-grams.

That external NLP stack has no place in a TPU framework image, so this node
reproduces the *pipeline behavior* (token -> lemma -> entity-substituted
n-grams) with a dependency-free rule engine:

- tokenization: word/number regex;
- lemmatization: a small English suffix stripper (plural/verb/adverb rules
  with a common-irregulars table) — intentionally lightweight, not Porter;
- entity substitution: numbers -> ``<NUM>``, capitalized non-sentence-initial
  tokens -> ``<ENT>`` (the same role CoreNLP's NER classes play in the
  reference's features).

The node is host-side; its output feeds the same TermFrequency /
CommonSparseFeatures path as the plain tokenizer.
"""

from __future__ import annotations

import functools
import re
from typing import ClassVar, List, Sequence, Tuple

import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.ops.nlp.ngrams import NGramsFeaturizer

_TOKEN_RE = re.compile(r"[A-Za-z]+|[0-9]+(?:\.[0-9]+)?")


@functools.lru_cache(maxsize=None)
def _featurizer(orders: Tuple[int, ...]) -> NGramsFeaturizer:
    # one immutable featurizer per orders tuple, not one per document
    return NGramsFeaturizer(orders=orders)

_IRREGULAR = {
    "is": "be", "are": "be", "was": "be", "were": "be", "been": "be", "am": "be",
    "has": "have", "had": "have", "having": "have",
    "does": "do", "did": "do", "done": "do", "doing": "do",
    "went": "go", "gone": "go", "goes": "go",
    "said": "say", "says": "say",
    "made": "make", "men": "man", "women": "woman", "children": "child",
    "mice": "mouse", "feet": "foot", "teeth": "tooth", "people": "person",
    "better": "good", "best": "good", "worse": "bad", "worst": "bad",
}


def lemmatize(word: str) -> str:
    """Rule-based English lemmatizer (lowercased input)."""
    w = word.lower()
    if w in _IRREGULAR:
        return _IRREGULAR[w]
    n = len(w)
    if n > 4 and w.endswith("ies"):
        return w[:-3] + "y"
    if n > 4 and w.endswith(("sses", "ches", "shes", "xes", "zes")):
        return w[:-2]
    if n > 3 and w.endswith("s") and not w.endswith(("ss", "us", "is")):
        return w[:-1]
    if n > 5 and w.endswith("ing"):
        stem = w[:-3]
        if len(stem) > 2 and stem[-1] == stem[-2]:  # running -> run
            stem = stem[:-1]
        return stem
    if n > 4 and w.endswith("ed"):
        stem = w[:-2]
        if len(stem) > 2 and stem[-1] == stem[-2]:  # stopped -> stop
            stem = stem[:-1]
        return stem
    if n > 4 and w.endswith("ly"):
        return w[:-2]
    return w


class CoreNLPFeatureExtractor(Transformer):
    """Text -> entity-substituted lemma n-grams (orders ``orders``)."""

    jittable: ClassVar[bool] = False
    orders: Tuple[int, ...] = struct.field(pytree_node=False, default=(1, 2))

    def apply(self, text: str) -> List[tuple]:
        tokens: List[str] = []
        sentence_start = True
        prev_end = 0
        for m in _TOKEN_RE.finditer(text):
            # sentence boundary lives in the raw text between tokens
            # ("bark. The" -> '. ' separates), not in the token itself
            if any(ch in ".!?" for ch in text[prev_end : m.start()]):
                sentence_start = True
            tok = m.group(0)
            if tok[0].isdigit():
                tokens.append("<NUM>")
            elif tok[0].isupper() and not sentence_start:
                tokens.append("<ENT>")
            else:
                tokens.append(lemmatize(tok))
            sentence_start = False
            prev_end = m.end()
        return _featurizer(tuple(self.orders)).apply(tokens)

    def apply_batch(self, texts: Sequence[str]) -> List[List[tuple]]:
        return [self.apply(t) for t in texts]
