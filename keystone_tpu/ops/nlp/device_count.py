"""Device-side keyed counting: sort + segment-reduce over packed int64 keys.

Reference: ``nodes/nlp/ngrams.scala:150-183`` (``NGramsCounts``: per-partition
``JHashMap`` counting merged by ``reduceByKey``) and
``StupidBackoff.scala:156-159`` (``reduceByKey`` under the backoff
partitioner). The reference counts on CPU executors with hash maps; here the
count *is* a device program — the same sort + segment-reduce XLA primitives
the scoring side already uses (``stupid_backoff.py``), so the whole
fit-to-score path runs on chip without per-n-gram host objects.

Everything is static-shape jittable: variable-size results (the set of
distinct keys) are returned **sentinel-padded** to the input length, with the
true size as a traced scalar. The sentinel is ``int64 max``, which is
strictly greater than any packable key, so padded tables remain valid inputs
to ``searchsorted``-based lookup (a padded slot can never equal a real query
key, and its count is 0).

All entry points require x64 (wrap calls in ``with jax.enable_x64():`` —
the packed-key convention of ``indexers.PackedNGramIndexer``).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL = np.iinfo(np.int64).max


def sentinel_for(dtype) -> int:
    """The padding sentinel for a key dtype — the single definition of the
    convention (``iinfo(dtype).max``; strictly above every packable key)."""
    return int(np.iinfo(np.dtype(jnp.dtype(dtype).name)).max)


def window_keys(
    ids: jnp.ndarray, lengths: jnp.ndarray, order: int, word_bits: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All order-``order`` n-gram windows of a padded id batch, as packed keys.

    ``ids``: int ``[D, L]`` (pad/OOV = -1), ``lengths``: ``[D]``. Returns
    ``(keys [D*(L-order+1)], valid bool [same])`` — the device analog of
    :func:`~keystone_tpu.ops.nlp.ngrams.encoded_ngrams` +
    ``PackedNGramIndexer.pack_batch`` fused: farthest word in the highest
    bits (lexicographic sort order). Windows that cross the true length or
    contain an OOV id are invalid. ``L < order`` yields empty outputs.

    Keys are int32 when ``order * word_bits <= 31`` (the downstream sort —
    the dominant cost — is ~2x cheaper in 32 bits), int64 otherwise; callers
    widen as needed.
    """
    # <= 30 (not 31): the int32 sentinel (2^31-1) must stay strictly above
    # every packable key
    dt = jnp.int32 if order * word_bits <= 30 else jnp.int64
    d, max_len = ids.shape
    w = max_len - order + 1
    if w <= 0:
        z = jnp.zeros((0,), dt)
        return z, jnp.zeros((0,), bool)
    key = ids[:, :w].astype(dt)
    ok = ids[:, :w] >= 0
    for j in range(1, order):
        nxt = ids[:, j : w + j]
        key = (key << word_bits) | jnp.where(nxt >= 0, nxt, 0).astype(dt)
        ok &= nxt >= 0
    pos = jnp.arange(w)[None, :]
    ok &= pos + order <= lengths[:, None]
    return key.reshape(-1), ok.reshape(-1)


def sum_by_key(
    keys: jnp.ndarray, valid: jnp.ndarray, weights: jnp.ndarray = None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Group-by-key sum on device: the ``reduceByKey`` primitive.

    Returns ``(uniq_keys [N], totals float32 [N], n_unique int32)``
    with ``N = len(keys)``: distinct valid keys in ascending order at the
    front, sentinel (``iinfo(dtype).max``) padding behind, per-key totals
    aligned (0 on padding). ``weights`` defaults to 1 per valid element
    (pure counting). Key dtype is preserved (int32 in, int32 out).
    """
    n = keys.shape[0]
    sentinel = sentinel_for(keys.dtype)
    if n == 0:
        return keys, jnp.zeros((0,), jnp.float32), jnp.int32(0)
    k = jnp.where(valid, keys, sentinel)
    if weights is None:
        # pure counting: the weight of a sorted element is just its validity,
        # which is positional after the sort (valid keys < SENTINEL sort to
        # the front) — no permutation needed
        s = jnp.sort(k)
        sw = (s != sentinel).astype(jnp.float32)
    else:
        # co-sort (key, weight) pairs in one pass (cheaper than
        # argsort + gather)
        s, sw = jax.lax.sort(
            (k, jnp.where(valid, weights.astype(jnp.float32), 0.0)), num_keys=1
        )
    isvalid = s != sentinel
    new = jnp.concatenate([isvalid[:1], (s[1:] != s[:-1]) & isvalid[1:]])
    seg = jnp.maximum(jnp.cumsum(new) - 1, 0)
    totals = jax.ops.segment_sum(sw, seg, num_segments=n)
    # scatter each boundary element's key to its segment slot; padding stays
    # sentinel (non-boundary writes are routed out of bounds and dropped)
    idx = jnp.where(new, seg, n)
    uniq = jnp.full((n,), sentinel, k.dtype).at[idx].set(s, mode="drop")
    return uniq, totals, new.sum().astype(jnp.int32)


def count_ngrams_device(
    ids: jnp.ndarray, lengths: jnp.ndarray, order: int, word_bits: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Count all order-``order`` n-grams of a padded batch on device.

    ``NGramsCounts`` for one order over encoded ids: returns sentinel-padded
    ``(uniq_keys, counts, n_unique)`` (see :func:`sum_by_key`).
    """
    keys, valid = window_keys(ids, lengths, order, word_bits)
    return sum_by_key(keys, valid)


@functools.partial(jax.jit, static_argnums=(1,))
def unigram_table_device(
    ids: jnp.ndarray, vocab_size: int, lengths: jnp.ndarray = None
) -> jnp.ndarray:
    """Dense per-id counts ``float32 [vocab_size]`` from a padded id batch.

    The device analog of ``WordFrequencyEncoder``'s unigram count map
    (``WordFrequencyEncoder.scala:13-30``); pad/OOV ids (< 0) are dropped.
    """
    flat = ids.reshape(-1)
    ok = flat >= 0
    if lengths is not None:
        pos = jnp.arange(ids.shape[1])[None, :] < lengths[:, None]
        ok &= pos.reshape(-1)
    return jax.ops.segment_sum(
        ok.astype(jnp.float32), jnp.where(ok, flat, 0), num_segments=vocab_size
    )


def frequency_rank_ids(
    ids: jnp.ndarray, counts: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Re-encode ids so id 0 is the most frequent word (device analog of the
    fitted ``WordFrequencyEncoder`` vocabulary ordering; ties broken by
    original id — the host encoder breaks them by first occurrence, which has
    no tensor analog and is documented as the one divergence).

    Returns ``(ranked_ids [same shape], ranked_counts [vocab])``; pad/OOV
    ids pass through unchanged.
    """
    rank_of = jnp.argsort(jnp.argsort(-counts, stable=True))
    ranked = jnp.where(ids >= 0, rank_of[jnp.maximum(ids, 0)], ids)
    return ranked, jnp.sort(counts)[::-1]
