"""Device-side keyed counting: sort + segment-reduce over packed int64 keys.

Reference: ``nodes/nlp/ngrams.scala:150-183`` (``NGramsCounts``: per-partition
``JHashMap`` counting merged by ``reduceByKey``) and
``StupidBackoff.scala:156-159`` (``reduceByKey`` under the backoff
partitioner). The reference counts on CPU executors with hash maps; here the
count *is* a device program — the same sort + segment-reduce XLA primitives
the scoring side already uses (``stupid_backoff.py``), so the whole
fit-to-score path runs on chip without per-n-gram host objects.

Everything is static-shape jittable: variable-size results (the set of
distinct keys) are returned **sentinel-padded** to the input length, with the
true size as a traced scalar. The sentinel is ``int64 max``, which is
strictly greater than any packable key, so padded tables remain valid inputs
to ``searchsorted``-based lookup (a padded slot can never equal a real query
key, and its count is 0).

All entry points require x64 (wrap calls in ``with jax.enable_x64():`` —
the packed-key convention of ``indexers.PackedNGramIndexer``).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL = np.iinfo(np.int64).max


def sentinel_for(dtype) -> int:
    """The padding sentinel for a key dtype — the single definition of the
    convention (``iinfo(dtype).max``; strictly above every packable key)."""
    return int(np.iinfo(np.dtype(jnp.dtype(dtype).name)).max)


def window_keys(
    ids: jnp.ndarray, lengths: jnp.ndarray, order: int, word_bits: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All order-``order`` n-gram windows of a padded id batch, as packed keys.

    ``ids``: int ``[D, L]`` (pad/OOV = -1), ``lengths``: ``[D]``. Returns
    ``(keys [D*(L-order+1)], valid bool [same])`` — the device analog of
    :func:`~keystone_tpu.ops.nlp.ngrams.encoded_ngrams` +
    ``PackedNGramIndexer.pack_batch`` fused: farthest word in the highest
    bits (lexicographic sort order). Windows that cross the true length or
    contain an OOV id are invalid. ``L < order`` yields empty outputs.

    Keys are int32 when ``order * word_bits <= 31`` (the downstream sort —
    the dominant cost — is ~2x cheaper in 32 bits), int64 otherwise; callers
    widen as needed.
    """
    # <= 30 (not 31): the int32 sentinel (2^31-1) must stay strictly above
    # every packable key
    dt = jnp.int32 if order * word_bits <= 30 else jnp.int64
    d, max_len = ids.shape
    w = max_len - order + 1
    if w <= 0:
        z = jnp.zeros((0,), dt)
        return z, jnp.zeros((0,), bool)
    key = ids[:, :w].astype(dt)
    ok = ids[:, :w] >= 0
    for j in range(1, order):
        nxt = ids[:, j : w + j]
        key = (key << word_bits) | jnp.where(nxt >= 0, nxt, 0).astype(dt)
        ok &= nxt >= 0
    pos = jnp.arange(w)[None, :]
    ok &= pos + order <= lengths[:, None]
    return key.reshape(-1), ok.reshape(-1)


def sum_by_key(
    keys: jnp.ndarray, valid: jnp.ndarray, weights: jnp.ndarray = None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Group-by-key sum on device: the ``reduceByKey`` primitive.

    Returns ``(uniq_keys [N], totals float32 [N], n_unique int32)``
    with ``N = len(keys)``: distinct valid keys in ascending order at the
    front, sentinel (``iinfo(dtype).max``) padding behind, per-key totals
    aligned (0 on padding). ``weights`` defaults to 1 per valid element
    (pure counting). Key dtype is preserved (int32 in, int32 out).
    """
    n = keys.shape[0]
    sentinel = sentinel_for(keys.dtype)
    if n == 0:
        return keys, jnp.zeros((0,), jnp.float32), jnp.int32(0)
    k = jnp.where(valid, keys, sentinel)
    if weights is None:
        # pure counting: the weight of a sorted element is just its validity,
        # which is positional after the sort (valid keys < SENTINEL sort to
        # the front) — no permutation needed
        s = jnp.sort(k)
        sw = (s != sentinel).astype(jnp.float32)
    else:
        # co-sort (key, weight) pairs in one pass (cheaper than
        # argsort + gather)
        s, sw = jax.lax.sort(
            (k, jnp.where(valid, weights.astype(jnp.float32), 0.0)), num_keys=1
        )
    isvalid = s != sentinel
    new = jnp.concatenate([isvalid[:1], (s[1:] != s[:-1]) & isvalid[1:]])
    seg = jnp.maximum(jnp.cumsum(new) - 1, 0)
    totals = jax.ops.segment_sum(sw, seg, num_segments=n)
    # scatter each boundary element's key to its segment slot; padding stays
    # sentinel (non-boundary writes are routed out of bounds and dropped)
    idx = jnp.where(new, seg, n)
    uniq = jnp.full((n,), sentinel, k.dtype).at[idx].set(s, mode="drop")
    return uniq, totals, new.sum().astype(jnp.int32)


def count_ngrams_device(
    ids: jnp.ndarray, lengths: jnp.ndarray, order: int, word_bits: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Count all order-``order`` n-grams of a padded batch on device.

    ``NGramsCounts`` for one order over encoded ids: returns sentinel-padded
    ``(uniq_keys, counts, n_unique)`` (see :func:`sum_by_key`).
    """
    keys, valid = window_keys(ids, lengths, order, word_bits)
    return sum_by_key(keys, valid)


# ---------------------------------------------------------------------------
# Mesh-sharded keyed aggregation — the cluster-wide reduceByKey.
#
# The reference's counting is a two-phase shuffle: per-partition hash-map
# combine, then ``reduceByKey`` routes each key to one reducer under a
# locality-aware partitioner (``ngrams.scala:150-183``,
# ``StupidBackoff.scala:25-57,156-159``). The TPU-native translation keeps
# the two phases but swaps the data plane for dense static-shape collectives:
#
#   phase 1 (combine)  — per-shard sort + segment-reduce (:func:`sum_by_key`
#                        on each device's rows), compacting n_local windows
#                        to <= n_local distinct (key, total) pairs;
#   phase 2 (exchange) — all-gather of the COMPACTED pair tables over ICI,
#                        then one merge reduce of the P·C gathered pairs.
#
# Why all-gather instead of a key-range all-to-all: XLA collectives are
# static-shape, so an exact all-to-all must provision every (src, dst)
# chunk for its worst case — a source whose distinct keys all land in one
# range — i.e. capacity n_local per chunk, P·n_local received: byte-for-byte
# the all-gather, with splitter logic on top. The all-gather form rides the
# ICI ring at full bandwidth, needs no splitters, and lands the merged table
# REPLICATED — which is the placement scoring wants anyway (every device
# binary-searches the full table; the reference re-broadcasts its reduced
# map for the same reason). What phase 1 buys is the ``capacity`` knob: with
# C < n_local (long documents repeat n-grams; distinct << windows) the
# exchange shrinks by n_local/C while staying exact as long as every shard's
# distinct count fits — overflow is REPORTED, never silent (``overflowed``).
# ---------------------------------------------------------------------------


def _compact_gather_merge(uniq_l, tot_l, nu_l, cap: int, axis: str):
    """Phase 2 of the sharded reduce (module design note), shared by every
    sharded entry point: truncate the per-shard compacted table to the
    capacity budget, flag overflow (pmax'd so every device agrees),
    all-gather the compacted (key, total) pairs over ``axis``, and merge
    with one weighted :func:`sum_by_key`. Call from inside ``shard_map``."""
    sentinel = sentinel_for(uniq_l.dtype)
    over = jax.lax.pmax((nu_l > cap).astype(jnp.int32), axis)
    gk = jax.lax.all_gather(uniq_l[:cap], axis, tiled=True)
    gt = jax.lax.all_gather(tot_l[:cap], axis, tiled=True)
    uniq, tot, nu = sum_by_key(gk, gk != sentinel, gt)
    return uniq, tot, nu, over


def pad_docs_to_mesh(ids, lengths, p: int):
    """Pad the document axis to a multiple of the mesh axis size with empty
    documents (ids -1, length 0 — no valid windows, no effect on counts).
    The shared ingest recipe of every sharded counting entry point."""
    pad = (-ids.shape[0]) % p
    if pad:
        ids = jnp.concatenate(
            [ids, jnp.full((pad, ids.shape[1]), -1, ids.dtype)]
        )
        lengths = jnp.concatenate([lengths, jnp.zeros((pad,), lengths.dtype)])
    return ids, lengths


def check_shard_capacity(overflowed, capacity) -> None:
    """Shared overflow contract: an undersized per-shard capacity RAISES
    (counts would be silently low otherwise); ``capacity=None`` is provably
    exact, so the host sync is skipped entirely."""
    if capacity is not None and int(overflowed):
        raise RuntimeError(
            f"shard_capacity={capacity} undersizes some shard's "
            "distinct-key count — refit with a larger capacity (None = "
            "exact)"
        )


def sum_by_key_sharded(
    keys: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    mesh,
    axis: str = "data",
    weights: jnp.ndarray = None,
    capacity: int = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Group-by-key sum across a device mesh (module-level design note).

    ``keys``/``valid``/``weights`` are global arrays row-sharded along
    ``axis`` (length divisible by the axis size). Returns
    ``(uniq_keys [P*C], totals [P*C], n_unique, overflowed)`` replicated on
    every device: distinct keys ascending at the front, sentinel padding
    behind — the same contract as :func:`sum_by_key`. ``capacity`` is the
    per-shard compaction budget C (default n_local = exact for any input);
    ``overflowed`` is nonzero iff some shard held more than C distinct keys,
    in which case totals are incomplete and the caller must refit with a
    larger capacity — checked, e.g., by
    ``StupidBackoffEstimator.fit_device``'s host sync.
    """
    from jax.sharding import PartitionSpec as P

    n = keys.shape[0]
    p = mesh.shape[axis]
    if n % p != 0:
        raise ValueError(f"global length {n} not divisible by mesh axis {p}")
    n_local = n // p
    cap = n_local if capacity is None else min(int(capacity), n_local)

    # weights=None keeps sum_by_key's cheaper single-array sort path (the
    # per-shard sort is the dominant cost) — don't manufacture a ones array
    if weights is None:
        def shard_fn(k_l, v_l):
            return _compact_gather_merge(*sum_by_key(k_l, v_l), cap, axis)

        in_specs = (P(axis), P(axis))
        args = (keys, valid)
    else:
        def shard_fn(k_l, v_l, w_l):
            return _compact_gather_merge(
                *sum_by_key(k_l, v_l, w_l), cap, axis
            )

        in_specs = (P(axis), P(axis), P(axis))
        args = (keys, valid, weights.astype(jnp.float32))
    rep = P()
    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        check_vma=False,  # outputs are deterministic fns of all-gathered
                          # (hence replicated) data; inference can't see it
        in_specs=in_specs,
        out_specs=(rep, rep, rep, rep),
    )(*args)


def count_ngrams_sharded(
    ids: jnp.ndarray,
    lengths: jnp.ndarray,
    order: int,
    word_bits: int,
    *,
    mesh,
    axis: str = "data",
    capacity: int = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`count_ngrams_device` across a document-sharded mesh.

    ``ids [D, L]`` / ``lengths [D]`` row-sharded along ``axis`` (windows
    never cross documents, so sharding the document axis is exact; a
    non-divisible D is padded with empty docs via
    :func:`pad_docs_to_mesh`). The window extraction runs per shard inside
    the same program; returns the replicated merged table (see
    :func:`sum_by_key_sharded`).
    """
    from jax.sharding import PartitionSpec as P

    p = mesh.shape[axis]
    ids, lengths = pad_docs_to_mesh(
        jnp.asarray(ids), jnp.asarray(lengths), p
    )
    d = ids.shape[0]
    w = ids.shape[1] - order + 1
    if w <= 0:
        dt = jnp.int32 if order * word_bits <= 30 else jnp.int64
        return (
            jnp.zeros((0,), dt),
            jnp.zeros((0,), jnp.float32),
            jnp.int32(0),
            jnp.int32(0),
        )
    n_local = (d // p) * w
    cap = n_local if capacity is None else min(int(capacity), n_local)

    def shard_fn(ids_l, len_l):
        k_l, v_l = window_keys(ids_l, len_l, order, word_bits)
        return _compact_gather_merge(*sum_by_key(k_l, v_l), cap, axis)

    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        check_vma=False,  # outputs are deterministic fns of all-gathered
                          # (hence replicated) data; inference can't see it
        in_specs=(P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()),
    )(ids, lengths)


def unigram_table_sharded(
    ids: jnp.ndarray,
    vocab_size: int,
    lengths: jnp.ndarray = None,
    *,
    mesh,
    axis: str = "data",
) -> jnp.ndarray:
    """:func:`unigram_table_device` across a document-sharded mesh: per-shard
    dense bincount + one psum (the vocab table is dense, so the merge is the
    cheap psum case of the design note — no key exchange at all)."""
    from jax.sharding import PartitionSpec as P

    def shard_fn(ids_l, len_l):
        local = unigram_table_device(ids_l, vocab_size, len_l)
        return jax.lax.psum(local, axis)

    if lengths is None:
        lengths = jnp.full((ids.shape[0],), ids.shape[1], jnp.int32)
    # same ingest recipe as the sibling entry points: length-0 padding docs
    # contribute nothing to the bincount, and a non-divisible doc count
    # would otherwise fail with an opaque shard_map sharding error
    ids, lengths = pad_docs_to_mesh(
        jnp.asarray(ids), jnp.asarray(lengths), mesh.shape[axis]
    )
    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        check_vma=False,  # outputs are deterministic fns of all-gathered
                          # (hence replicated) data; inference can't see it
        in_specs=(P(axis), P(axis)),
        out_specs=P(),
    )(ids, lengths)


@functools.partial(jax.jit, static_argnums=(1,))
def unigram_table_device(
    ids: jnp.ndarray, vocab_size: int, lengths: jnp.ndarray = None
) -> jnp.ndarray:
    """Dense per-id counts ``float32 [vocab_size]`` from a padded id batch.

    The device analog of ``WordFrequencyEncoder``'s unigram count map
    (``WordFrequencyEncoder.scala:13-30``); pad/OOV ids (< 0) are dropped.
    """
    flat = ids.reshape(-1)
    ok = flat >= 0
    if lengths is not None:
        pos = jnp.arange(ids.shape[1])[None, :] < lengths[:, None]
        ok &= pos.reshape(-1)
    return jax.ops.segment_sum(
        ok.astype(jnp.float32), jnp.where(ok, flat, 0), num_segments=vocab_size
    )


def frequency_rank_ids(
    ids: jnp.ndarray, counts: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Re-encode ids so id 0 is the most frequent word (device analog of the
    fitted ``WordFrequencyEncoder`` vocabulary ordering; ties broken by
    original id — the host encoder breaks them by first occurrence, which has
    no tensor analog and is documented as the one divergence).

    Returns ``(ranked_ids [same shape], ranked_counts [vocab])``; pad/OOV
    ids pass through unchanged.
    """
    rank_of = jnp.argsort(jnp.argsort(-counts, stable=True))
    ranked = jnp.where(ids >= 0, rank_of[jnp.maximum(ids, 0)], ids)
    return ranked, jnp.sort(counts)[::-1]
