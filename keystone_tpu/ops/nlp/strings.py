"""String preprocessing nodes: Tokenizer / Trim / LowerCase.

Reference: ``nodes/nlp/StringUtils.scala:13,20,28`` — regex split, trim,
lowercase over ``RDD[String]``.

Strings never reach the TPU: these are host-side nodes (``jittable = False``)
whose bulk path maps over a Python list. Everything downstream of
:class:`~keystone_tpu.ops.nlp.word_frequency.WordFrequencyEncoder` is integer
tensors and runs on device.
"""

from __future__ import annotations

import re
from typing import ClassVar, List, Sequence

import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer


class Trim(Transformer):
    """``_.trim`` (``StringUtils.scala:20``)."""

    jittable: ClassVar[bool] = False

    def apply(self, x: str) -> str:
        return x.strip()

    def apply_batch(self, xs: Sequence[str]) -> List[str]:
        return [x.strip() for x in xs]


class LowerCase(Transformer):
    """``_.toLowerCase`` (``StringUtils.scala:28``)."""

    jittable: ClassVar[bool] = False

    def apply(self, x: str) -> str:
        return x.lower()

    def apply_batch(self, xs: Sequence[str]) -> List[str]:
        return [x.lower() for x in xs]


class Tokenizer(Transformer):
    """Regex-split tokenizer (``StringUtils.scala:13``; default ``"[\\s]+"``).

    Matches the reference's ``String.split(pattern)`` semantics: split on the
    pattern, drop trailing empty strings (Java ``split`` behavior), keep a
    leading empty token when the string starts with a separator.
    """

    jittable: ClassVar[bool] = False
    pattern: str = struct.field(pytree_node=False, default="[\\s]+")

    def apply(self, x: str) -> List[str]:
        toks = re.split(self.pattern, x)
        # Java split drops trailing empties only.
        while toks and toks[-1] == "":
            toks.pop()
        return toks

    def apply_batch(self, xs: Sequence[str]) -> List[List[str]]:
        return [self.apply(x) for x in xs]
