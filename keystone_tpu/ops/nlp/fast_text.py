"""Fused host-side text featurization over packed integer n-gram keys.

Semantically equivalent to the reference's text-classification chain

    Trim >> LowerCase >> Tokenizer >> NGramsFeaturizer(orders)
        >> TermFrequency(weight) >> CommonSparseFeatures(k)

(``pipelines/text/NewsgroupsPipeline.scala:24-32``; node cites in
``strings.py`` / ``ngrams.py`` / ``ops/util/sparse.py``) but executed as one
vectorized pass: tokens are dictionary-encoded to int ids once, n-grams become
base-``V`` packed int64 keys formed by strided numpy ops, and counting /
top-K selection / vectorization are ``lexsort``/``unique``/``searchsorted``
over flat arrays. No per-n-gram Python objects exist anywhere, which is the
entire cost of the tuple path (profiling: tuple formation + Counter +
most_common + per-row dict lookups ≈ 90% of the host wall-clock).

The output is the same padded-COO :class:`~keystone_tpu.ops.util.sparse.SparseBatch`
(rows sorted by feature id, unknown test-time terms dropped), so everything
downstream — NaiveBayes fit/score, MaxClassifier, evaluators — is unchanged.
``tests/test_newsgroups.py`` pins exact equivalence against the tuple chain.
"""

from __future__ import annotations

import re
from typing import ClassVar, Dict, List, Optional, Sequence, Tuple

import flax.struct as struct
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer
from keystone_tpu.ops.util.sparse import SparseBatch

_WEIGHTS = ("binary", "count")


def _tokenize_encode(
    docs: Sequence[str], pattern: str, vocab: Dict[str, int], grow: bool
) -> Tuple[np.ndarray, np.ndarray]:
    """Trim+lower+regex-split each doc and dictionary-encode tokens.

    Returns (flat_ids int64 [T], doc_of int64 [T]). Unknown tokens when
    ``grow=False`` encode as -1 (any n-gram containing one is dropped later —
    it cannot be in the fitted feature space). Token semantics match
    :class:`~keystone_tpu.ops.nlp.strings.Tokenizer`: trailing empty strings
    dropped, leading empty kept (Java ``String.split``).
    """
    split = re.compile(pattern).split
    flat: List[int] = []
    lengths = np.empty(len(docs), np.int64)
    if grow:
        for i, x in enumerate(docs):
            toks = split(x.strip().lower())
            while toks and toks[-1] == "":
                toks.pop()
            n0 = len(flat)
            flat.extend(vocab.setdefault(t, len(vocab)) for t in toks)
            lengths[i] = len(flat) - n0
    else:
        get = vocab.get
        for i, x in enumerate(docs):
            toks = split(x.strip().lower())
            while toks and toks[-1] == "":
                toks.pop()
            n0 = len(flat)
            flat.extend(get(t, -1) for t in toks)
            lengths[i] = len(flat) - n0
    ids = np.asarray(flat, dtype=np.int64)
    doc_of = np.repeat(np.arange(len(docs), dtype=np.int64), lengths)
    return ids, doc_of


def _ngram_keys(
    ids: np.ndarray, doc_of: np.ndarray, orders: Tuple[int, ...], base: int
) -> Tuple[np.ndarray, np.ndarray]:
    """All n-grams of the given orders as packed int64 keys.

    key = Horner(base) over the window's ids, then ``* n_orders + order_index``
    so different orders can never collide. Windows crossing a document
    boundary or containing an unknown (-1) id are dropped.
    """
    n_orders = len(orders)
    max_order = max(orders)
    if base > 1 and n_orders * base ** max_order >= 2 ** 63:
        raise OverflowError(
            f"vocab size {base - 1} with order {max_order} overflows int64 key "
            "packing; use the tuple-based NGramsFeaturizer chain instead"
        )
    keys_out, docs_out = [], []
    T = len(ids)
    for oi, o in enumerate(orders):
        m = T - o + 1
        if m <= 0:
            continue
        k = ids[:m].copy()
        ok = ids[:m] >= 0
        for j in range(1, o):
            k *= base
            k += ids[j : m + j]
            ok &= ids[j : m + j] >= 0
        if o > 1:
            ok &= doc_of[:m] == doc_of[o - 1 :]
        k *= n_orders
        k += oi
        keys_out.append(k[ok])
        docs_out.append(doc_of[:m][ok])
    if not keys_out:
        z = np.zeros(0, np.int64)
        return z, z.copy()
    return np.concatenate(keys_out), np.concatenate(docs_out)


def _per_doc_weights(
    keys: np.ndarray, docs: np.ndarray, weight: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Collapse (doc, key) occurrences to one weighted entry per distinct pair.

    Returns (uniq_keys, uniq_docs, weights): ``binary`` → 1.0 per distinct
    (doc, term) (the reference pipeline's ``x => 1``), ``count`` → the raw
    per-doc count (``identity_weight``).
    """
    if len(keys) == 0:
        return keys, docs, np.zeros(0, np.float32)
    order = np.lexsort((keys, docs))
    k_s, d_s = keys[order], docs[order]
    is_new = np.empty(len(k_s), bool)
    is_new[0] = True
    np.logical_or(d_s[1:] != d_s[:-1], k_s[1:] != k_s[:-1], out=is_new[1:])
    starts = np.flatnonzero(is_new)
    uniq_keys, uniq_docs = k_s[starts], d_s[starts]
    if weight == "binary":
        w = np.ones(len(starts), np.float32)
    else:
        w = np.diff(np.append(starts, len(k_s))).astype(np.float32)
    return uniq_keys, uniq_docs, w


def _to_sparse_batch(
    feats: np.ndarray, docs: np.ndarray, weights: np.ndarray, n_docs: int, num_features: int
) -> SparseBatch:
    """Pack per-(doc, feature, weight) triples into a padded-COO batch with
    rows sorted by feature id (matching ``SparseFeatureVectorizer``)."""
    order = np.lexsort((feats, docs))
    d, f, w = docs[order], feats[order], weights[order]
    row_counts = np.bincount(d, minlength=n_docs).astype(np.int64)
    max_nnz = max(1, int(row_counts.max()) if len(row_counts) else 1)
    starts = np.cumsum(row_counts) - row_counts  # length n_docs, empty-safe
    col = np.arange(len(d), dtype=np.int64) - np.repeat(starts, row_counts)
    indices = np.full((n_docs, max_nnz), -1, np.int32)
    values = np.zeros((n_docs, max_nnz), np.float32)
    indices[d, col] = f.astype(np.int32)
    values[d, col] = w
    return SparseBatch(
        indices=jnp.asarray(indices), values=jnp.asarray(values), num_features=num_features
    )


def _lookup_and_batch(
    keys_sorted: np.ndarray,
    feat_of_key: np.ndarray,
    uk: np.ndarray,
    ud: np.ndarray,
    w: np.ndarray,
    n_docs: int,
) -> SparseBatch:
    """Map collapsed (doc, key, weight) entries into the fitted feature space
    (misses dropped) and pack as a padded-COO batch."""
    pos = np.searchsorted(keys_sorted, uk)
    if len(keys_sorted):
        pos_c = np.minimum(pos, len(keys_sorted) - 1)
        hit = (pos < len(keys_sorted)) & (keys_sorted[pos_c] == uk)
    else:
        pos_c = pos
        hit = np.zeros(len(uk), bool)
    return _to_sparse_batch(
        feat_of_key[pos_c[hit]], ud[hit], w[hit], n_docs, len(keys_sorted)
    )


class EncodedNGramVectorizer(Transformer):
    """Fitted fused featurizer: raw docs → :class:`SparseBatch`.

    State: the token vocabulary, the packing base, and the selected feature
    keys (ascending, with their assigned feature ids). All statics are plain
    dict/ndarray — checkpointable without a callable registry.
    """

    jittable: ClassVar[bool] = False
    vocab: Dict[str, int] = struct.field(pytree_node=False)
    base: int = struct.field(pytree_node=False)
    orders: Tuple[int, ...] = struct.field(pytree_node=False)
    pattern: str = struct.field(pytree_node=False)
    weight: str = struct.field(pytree_node=False)
    keys_sorted: np.ndarray = struct.field(pytree_node=False)
    feat_of_key: np.ndarray = struct.field(pytree_node=False)

    @property
    def num_features(self) -> int:
        return len(self.keys_sorted)

    def apply_batch(self, docs: Sequence[str]) -> SparseBatch:
        ids, doc_of = _tokenize_encode(docs, self.pattern, self.vocab, grow=False)
        keys, kdocs = _ngram_keys(ids, doc_of, self.orders, self.base)
        uk, ud, w = _per_doc_weights(keys, kdocs, self.weight)
        return _lookup_and_batch(
            self.keys_sorted, self.feat_of_key, uk, ud, w, len(docs)
        )

    def apply(self, doc: str) -> SparseBatch:
        return self.apply_batch([doc])


class EncodedCommonSparseFeatures(Estimator):
    """Fused estimator for the whole reference text chain (see module doc).

    ``weight``: ``"binary"`` (the newsgroups pipeline's ``x => 1``) or
    ``"count"``. Top-``num_features`` n-grams by total weight are kept, ids
    assigned in descending-total order (mirroring ``Counter.most_common`` in
    ``CommonSparseFeatures.fit``). Ties *at the cut* are broken arbitrarily
    (``np.argpartition``), just as the reference's ``most_common`` breaks them
    by insertion order — only the id assignment among *selected* features is
    made deterministic (stable lexsort on key).
    """

    def __init__(
        self,
        orders: Tuple[int, ...] = (1, 2),
        num_features: int = 100000,
        weight: str = "binary",
        pattern: str = "[\\s]+",
    ):
        if weight not in _WEIGHTS:
            raise ValueError(f"weight must be one of {_WEIGHTS}, got {weight!r}")
        orders = tuple(orders)
        if not orders or min(orders) < 1:
            raise ValueError(f"orders must be >= 1, got {orders}")
        self.orders = orders
        self.num_features = int(num_features)
        self.weight = weight
        self.pattern = pattern

    def fit(self, docs: Sequence[str]) -> EncodedNGramVectorizer:
        return self._fit_core(docs)[0]

    def fit_transform(
        self, docs: Sequence[str]
    ) -> Tuple[EncodedNGramVectorizer, SparseBatch]:
        """Fit and also return the train-set batch (one tokenize/encode pass
        instead of the fit-then-transform double pass)."""
        vec, uk, ud, w = self._fit_core(docs)
        batch = _lookup_and_batch(
            vec.keys_sorted, vec.feat_of_key, uk, ud, w, len(docs)
        )
        return vec, batch

    def _fit_core(self, docs: Sequence[str]):
        vocab: Dict[str, int] = {}
        ids, doc_of = _tokenize_encode(docs, self.pattern, vocab, grow=True)
        base = len(vocab) + 1
        keys, kdocs = _ngram_keys(ids, doc_of, self.orders, base)
        uk, ud, w = _per_doc_weights(keys, kdocs, self.weight)

        # keyed aggregation via the native multithreaded reducer (sorted
        # distinct keys + totals; numpy fallback inside)
        from keystone_tpu.native.ngram import count_by_key

        distinct, totals = count_by_key(uk, w.astype(np.float64))
        if self.num_features < len(distinct):
            cut = np.argpartition(-totals, self.num_features - 1)[: self.num_features]
            distinct, totals = distinct[cut], totals[cut]
        # feature ids in descending-total order (stable on key for determinism)
        rank = np.lexsort((distinct, -totals))
        keys_sorted = np.sort(distinct)
        feat_ids = np.empty(len(distinct), np.int32)
        feat_ids[np.searchsorted(keys_sorted, distinct[rank])] = np.arange(
            len(distinct), dtype=np.int32
        )
        vec = EncodedNGramVectorizer(
            vocab=vocab,
            base=base,
            orders=self.orders,
            pattern=self.pattern,
            weight=self.weight,
            keys_sorted=keys_sorted,
            feat_of_key=feat_ids,
        )
        return vec, uk, ud, w
