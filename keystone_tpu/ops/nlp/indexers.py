"""N-gram indexers: pack word-id n-grams into integer keys.

Reference: ``nodes/nlp/indexers.scala`` —

- ``BackoffIndexer`` trait (``indexers.scala:22-46``): ``pack`` / ``unpack`` /
  ``removeFarthestWord`` / ``removeCurrentWord`` / ``ngramOrder``.
- ``NaiveBitPackIndexer`` (``indexers.scala:49-112``): up to 3 word ids of
  20 bits each plus 4 control bits in one 64-bit key.
- ``NGramIndexerImpl`` (``indexers.scala:115-135``): sequence-based, order <= 5.

The TPU-native addition is :class:`PackedNGramIndexer`: vocab-sized bit-widths
and *vectorized* packing of whole ``[B, order]`` id batches into int64 key
tensors. Packed keys are what make the language model a device program — count
tables become sorted int64 arrays and lookup becomes ``searchsorted`` on the
TPU (see ``stupid_backoff.py``), replacing the reference's ``reduceByKey``
shuffle and per-partition hash maps.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

WORD_BITS = 20
WORD_MASK = (1 << WORD_BITS) - 1
MAX_NAIVE_ORDER = 3
ORDER_SHIFT = 3 * WORD_BITS  # control bits live above the three word slots


class BackoffIndexer:
    """Protocol shared by all indexers (``indexers.scala:22-46``)."""

    min_order: int = 1
    max_order: int = 2

    def pack(self, ngram: Sequence[int]):
        raise NotImplementedError

    def unpack(self, key) -> Tuple[int, ...]:
        raise NotImplementedError

    def ngram_order(self, key) -> int:
        raise NotImplementedError

    def remove_farthest_word(self, key):
        """Drop the leftmost (farthest-context) word: (a,b,c) -> (b,c)."""
        raise NotImplementedError

    def remove_current_word(self, key):
        """Drop the rightmost (current) word: (a,b,c) -> (a,b)."""
        raise NotImplementedError


class NaiveBitPackIndexer(BackoffIndexer):
    """Bit-pack <=3 word ids (20 bits each) + order bits into one int.

    Layout (ours, not a copy of the reference's): the *current* word occupies
    the low 20 bits, earlier context words the next slots, and the order the
    bits above ``ORDER_SHIFT``. This makes ``remove_current_word`` a right
    shift and ``remove_farthest_word`` a mask — both O(1), both vectorizable.
    """

    min_order = 1
    max_order = MAX_NAIVE_ORDER

    def pack(self, ngram: Sequence[int]) -> int:
        order = len(ngram)
        if not 1 <= order <= MAX_NAIVE_ORDER:
            raise ValueError(f"order must be 1..{MAX_NAIVE_ORDER}, got {order}")
        key = 0
        # ngram[-1] is the current word -> low bits.
        for i, w in enumerate(reversed(ngram)):
            if not 0 <= w <= WORD_MASK:
                raise ValueError(f"word id {w} out of 20-bit range")
            key |= (w + 0) << (i * WORD_BITS)
        return key | (order << ORDER_SHIFT)

    def ngram_order(self, key: int) -> int:
        return key >> ORDER_SHIFT

    def unpack(self, key: int) -> Tuple[int, ...]:
        order = self.ngram_order(key)
        return tuple(
            (key >> (i * WORD_BITS)) & WORD_MASK for i in range(order - 1, -1, -1)
        )

    def remove_farthest_word(self, key: int) -> int:
        order = self.ngram_order(key)
        if order < 2:
            raise ValueError("cannot shorten a unigram")
        new_order = order - 1
        payload = key & ((1 << (new_order * WORD_BITS)) - 1)
        return payload | (new_order << ORDER_SHIFT)

    def remove_current_word(self, key: int) -> int:
        order = self.ngram_order(key)
        if order < 2:
            raise ValueError("cannot shorten a unigram")
        payload = (key & ~(-1 << ORDER_SHIFT)) >> WORD_BITS
        return payload | ((order - 1) << ORDER_SHIFT)


class NGramIndexerImpl(BackoffIndexer):
    """Sequence-based indexer, order <= 5 (``indexers.scala:115-135``)."""

    min_order = 1
    max_order = 5

    def pack(self, ngram: Sequence[int]) -> Tuple[int, ...]:
        if not self.min_order <= len(ngram) <= self.max_order:
            raise ValueError(f"order must be 1..{self.max_order}")
        return tuple(ngram)

    def unpack(self, key: Tuple[int, ...]) -> Tuple[int, ...]:
        return tuple(key)

    def ngram_order(self, key: Tuple[int, ...]) -> int:
        return len(key)

    def remove_farthest_word(self, key: Tuple[int, ...]) -> Tuple[int, ...]:
        return tuple(key[1:])

    def remove_current_word(self, key: Tuple[int, ...]) -> Tuple[int, ...]:
        return tuple(key[:-1])


class PackedNGramIndexer:
    """Vocab-sized vectorized packing: ``[B, order]`` int ids -> int64 keys.

    Bit width per word is ``ceil(log2(vocab_size + 1))`` (id ``vocab_size`` is
    reserved so that every real id is distinguishable from an empty slot);
    ``order * bits`` must fit in 63 bits (raises ``ValueError`` otherwise).
    For a 1M-word vocab that allows orders up to 3; a 256k vocab order 3; a
    4k vocab order 5. ``StupidBackoffEstimator`` catches the overflow and
    falls back to tuple-keyed host tables.

    Keys of the same order sort lexicographically by (farthest, ..., current)
    word, so a sorted key table supports binary-search lookup on device.
    """

    def __init__(self, vocab_size: int, max_order: int):
        self.vocab_size = int(vocab_size)
        self.max_order = int(max_order)
        self.word_bits = max(1, int(np.ceil(np.log2(self.vocab_size + 1))))
        if self.word_bits * self.max_order > 63:
            raise ValueError(
                f"cannot pack order-{max_order} ngrams over a {vocab_size}-word "
                f"vocab into 63 bits ({self.word_bits} bits/word)"
            )

    def pack_batch(self, ngrams: np.ndarray) -> np.ndarray:
        """``ngrams``: integer ``[B, order]`` (same order per call) -> int64 ``[B]``.

        Farthest word lands in the highest bits (lexicographic sort order).
        Works identically on numpy and jax arrays (pure shifts/adds).
        """
        order = ngrams.shape[-1]
        keys = ngrams[..., 0].astype(np.int64)
        for i in range(1, order):
            keys = (keys << self.word_bits) | ngrams[..., i].astype(np.int64)
        return keys

    def drop_current_batch(self, keys: np.ndarray) -> np.ndarray:
        """Packed ``remove_current_word``: order-n keys -> order-(n-1) keys."""
        return keys >> self.word_bits

    def drop_farthest_batch(self, keys: np.ndarray, order: int) -> np.ndarray:
        """Packed ``remove_farthest_word`` for keys of the given order."""
        mask = (np.int64(1) << (self.word_bits * (order - 1))) - np.int64(1)
        return keys & mask
