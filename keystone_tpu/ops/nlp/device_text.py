"""Device-side fused text featurization over packed integer n-gram keys.

The same reference chain as ``fast_text.py`` —

    Trim >> LowerCase >> Tokenizer >> NGramsFeaturizer(orders)
        >> TermFrequency(weight) >> CommonSparseFeatures(k)

(``pipelines/text/NewsgroupsPipeline.scala:24-32``) — but executed as XLA
sort/segment programs on the accelerator instead of numpy on the host:
n-gram packing is elementwise Horner arithmetic, per-(doc, term) collapse and
per-term totals are one two-key ``lax.sort`` + boundary-flag segment sums
(``device_count.py``'s counting idiom), top-K selection is ``lax.top_k``, and
vectorization scatters straight into the padded-COO
:class:`~keystone_tpu.ops.util.sparse.SparseBatch` that NaiveBayes consumes.
Strings still stop at the host vocabulary encoder (the documented host/device
frontier, ``word_frequency.py``); everything after the id tensor runs on
device.

Key values are bit-for-bit the host path's (``fast_text._ngram_keys``:
Horner base-``V`` then ``* n_orders + order_index``), so fit equivalence
against :class:`~keystone_tpu.ops.nlp.fast_text.EncodedCommonSparseFeatures`
is testable on raw keys; like the host paths, ties *at the top-K cut* are
broken arbitrarily (``lax.top_k`` by position vs ``np.argpartition``).
"""

from __future__ import annotations

import functools
from typing import ClassVar, Optional, Sequence, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer
from keystone_tpu.ops.util.sparse import SparseBatch

_WEIGHTS = ("binary", "count")


def _key_dtype(base: int, orders: Tuple[int, ...]):
    """int32 when every packed key fits below the int32 sentinel (sorts are
    ~2x cheaper and searchsorted ~19x, measured on v5e); int64 otherwise;
    ``OverflowError`` past 63 bits (callers fall back to the host tuple
    chain, matching ``fast_text._ngram_keys``)."""
    span = len(orders) * base ** max(orders)
    if span <= 2**31 - 1:
        return jnp.int32
    if base > 1 and span >= 2**63:
        raise OverflowError(
            f"vocab size {base - 1} with order {max(orders)} overflows int64 "
            "key packing; use the tuple-based NGramsFeaturizer chain instead"
        )
    return jnp.int64


def _x64_if_needed(base: int, orders):
    """int64 keys only exist under ``jax.enable_x64`` (jax's default 32-bit
    mode silently canonicalizes jnp.int64 to int32 — the Horner packing
    would wrap and distinct n-grams would collide on exactly the real-corpus
    vocab sizes the int64 path exists for). No-op for int32-packable
    configs."""
    from contextlib import nullcontext

    return jax.enable_x64() if _key_dtype(base, tuple(orders)) == jnp.int64 \
        else nullcontext()


def _pack_orders(
    ids: jnp.ndarray,
    lengths: jnp.ndarray,
    orders: Tuple[int, ...],
    base: int,
    dt,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """All requested orders' n-gram windows as one flat (key, doc, valid)
    triple. Key construction matches ``fast_text._ngram_keys`` bit-for-bit:
    Horner over the window in base ``base``, then ``* n_orders + oi``."""
    n_orders = len(orders)
    d, max_len = ids.shape
    keys, docs, valid = [], [], []
    doc_ids = jnp.arange(d, dtype=jnp.int32)[:, None]
    for oi, o in enumerate(orders):
        w = max_len - o + 1
        if w <= 0:
            continue
        k = ids[:, :w].astype(dt)
        ok = ids[:, :w] >= 0
        for j in range(1, o):
            nxt = ids[:, j : w + j]
            k = k * base + jnp.where(nxt >= 0, nxt, 0).astype(dt)
            ok &= nxt >= 0
        k = k * n_orders + oi
        pos = jnp.arange(w)[None, :]
        ok &= pos + o <= lengths[:, None]
        keys.append(k.reshape(-1))
        docs.append(jnp.broadcast_to(doc_ids, (d, w)).reshape(-1))
        valid.append(ok.reshape(-1))
    return jnp.concatenate(keys), jnp.concatenate(docs), jnp.concatenate(valid)


def _searchsorted(table: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    method = "sort" if table.dtype == jnp.int32 else "scan"
    return jnp.searchsorted(table, q, method=method)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _fit_totals(ids, lengths, orders: Tuple[int, ...], base: int, weight: str):
    """Distinct keys + per-key totals over the whole corpus, one program.

    binary: a key's total = number of distinct docs containing it (the
    reference pipeline's ``x => 1`` followed by summation in
    ``CommonSparseFeatures.fit``); count: total occurrences.
    Returns sentinel-padded ``(distinct [N], totals [N], n_keys)``.
    """
    dt = _key_dtype(base, orders)
    sentinel = np.iinfo(np.int32 if dt == jnp.int32 else np.int64).max
    keys, docs, valid = _pack_orders(ids, lengths, orders, base, dt)
    n = keys.shape[0]
    k = jnp.where(valid, keys, sentinel)
    d = jnp.where(valid, docs, 0)
    sk, sd = jax.lax.sort((k, d), num_keys=2)
    isvalid = sk != sentinel
    if weight == "binary":
        w_elem = jnp.concatenate(
            [isvalid[:1], ((sk[1:] != sk[:-1]) | (sd[1:] != sd[:-1])) & isvalid[1:]]
        ).astype(jnp.float32)
    else:
        w_elem = isvalid.astype(jnp.float32)
    key_new = jnp.concatenate([isvalid[:1], (sk[1:] != sk[:-1]) & isvalid[1:]])
    key_seg = jnp.maximum(jnp.cumsum(key_new) - 1, 0)
    totals = jax.ops.segment_sum(w_elem, key_seg, num_segments=n)
    idx = jnp.where(key_new, key_seg, n)
    distinct = jnp.full((n,), sentinel, dt).at[idx].set(sk, mode="drop")
    return distinct, totals, key_new.sum().astype(jnp.int32)


def _fit_totals_sharded(
    ids, lengths, orders: Tuple[int, ...], base: int, weight: str,
    mesh, axis: str, capacity: Optional[int] = None,
):
    """:func:`_fit_totals` across a document-sharded mesh: per-shard
    distinct+totals (both weightings are doc-local — each document lives in
    exactly one shard, so per-shard doc-frequencies sum to the global ones),
    then compacted-table all-gather + merge reduce (the cluster-wide
    ``reduceByKey``; design note in ``device_count.py``). Returns
    ``(distinct, totals, n_keys, overflowed)`` replicated."""
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.ops.nlp.device_count import (
        _compact_gather_merge,
        pad_docs_to_mesh,
    )

    p = mesh.shape[axis]
    ids, lengths = pad_docs_to_mesh(ids, lengths, p)
    d, max_len = ids.shape
    n_local = (d // p) * sum(max(0, max_len - o + 1) for o in orders)
    cap = n_local if capacity is None else min(int(capacity), n_local)

    def shard_fn(ids_l, len_l):
        return _compact_gather_merge(
            *_fit_totals(ids_l, len_l, orders, base, weight), cap, axis
        )

    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        check_vma=False,  # outputs are deterministic fns of all-gathered
                          # (hence replicated) data; inference can't see it
        in_specs=(P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()),
    )(ids, lengths)


@functools.partial(jax.jit, static_argnums=(2,))
def _select_top_k(distinct, totals, k: int):
    """Top-``k`` keys by total weight; feature ids in descending-total order
    (ties by ascending key — the host path's ``np.lexsort((distinct,
    -totals))``). Returns ``(keys_sorted [k], feat_of_pos [k])``: the sorted
    key table and the feature id at each table position."""
    vals, idx = jax.lax.top_k(totals, k)
    sel_keys = distinct[idx]
    rank = jnp.lexsort((sel_keys, -vals))
    keys_sorted = jnp.sort(sel_keys)
    pos = _searchsorted(keys_sorted, sel_keys[rank])
    feat_of_pos = (
        jnp.zeros((k,), jnp.int32).at[pos].set(jnp.arange(k, dtype=jnp.int32))
    )
    return keys_sorted, feat_of_pos


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _vectorize(
    ids,
    lengths,
    keys_sorted,
    feat_of_pos,
    orders: Tuple[int, ...],
    base: int,
    weight: str,
    max_nnz: int,
):
    """Encoded id batch -> padded-COO (indices, values), all on device.

    Collapse to distinct (doc, key) pairs (sorted two-key pass), look each
    pair up in the fitted table (misses dropped — unknown test-time terms),
    then re-sort hits by (doc, feature) and scatter into rows; rows come out
    sorted by feature id like ``SparseFeatureVectorizer``.
    """
    dt = _key_dtype(base, orders)
    sentinel = np.iinfo(np.int32 if dt == jnp.int32 else np.int64).max
    kfeat = keys_sorted.shape[0]
    n_docs = ids.shape[0]
    keys, docs, valid = _pack_orders(ids, lengths, orders, base, dt)
    n = keys.shape[0]
    k = jnp.where(valid, keys, sentinel)
    d = jnp.where(valid, docs, 0)
    sk, sd = jax.lax.sort((k, d), num_keys=2)
    isvalid = sk != sentinel
    pair_new = jnp.concatenate(
        [isvalid[:1], ((sk[1:] != sk[:-1]) | (sd[1:] != sd[:-1])) & isvalid[1:]]
    )
    if weight == "binary":
        w_at = jnp.ones((n,), jnp.float32)
    else:
        pair_seg = jnp.maximum(jnp.cumsum(pair_new) - 1, 0)
        pair_tot = jax.ops.segment_sum(
            isvalid.astype(jnp.float32), pair_seg, num_segments=n
        )
        w_at = pair_tot[pair_seg]
    pos = jnp.clip(_searchsorted(keys_sorted, sk), 0, kfeat - 1)
    hit = (keys_sorted[pos] == sk) & pair_new
    # re-sort surviving (doc, feature, weight) entries by (doc, feature);
    # misses/duplicates get doc = n_docs and fall off the scatter
    d2 = jnp.where(hit, sd, n_docs)
    f2 = jnp.where(hit, feat_of_pos[pos], kfeat)
    sd2, sf2, sw2 = jax.lax.sort((d2, f2, w_at), num_keys=2)
    ok2 = sd2 < n_docs
    idx = jnp.arange(n)
    doc_new = jnp.concatenate([ok2[:1], (sd2[1:] != sd2[:-1]) & ok2[1:]])
    start = jax.lax.cummax(jnp.where(doc_new, idx, 0))
    col = idx - start
    indices = (
        jnp.full((n_docs, max_nnz), -1, jnp.int32)
        .at[sd2, col]
        .set(sf2, mode="drop")
    )
    values = (
        jnp.zeros((n_docs, max_nnz), jnp.float32).at[sd2, col].set(sw2, mode="drop")
    )
    return indices, values


class DeviceNGramVectorizer(Transformer):
    """Fitted device featurizer: encoded id batches -> :class:`SparseBatch`.

    State is two device arrays (the sorted selected-key table and the feature
    id at each table position) plus static packing parameters — a pytree,
    checkpointable like any fitted node.
    """

    jittable: ClassVar[bool] = False
    keys_sorted: jnp.ndarray
    feat_of_pos: jnp.ndarray
    base: int = struct.field(pytree_node=False)
    orders: Tuple[int, ...] = struct.field(pytree_node=False)
    weight: str = struct.field(pytree_node=False)

    @property
    def num_features(self) -> int:
        return int(self.keys_sorted.shape[0])

    def apply_encoded(self, ids, lengths) -> SparseBatch:
        ids = jnp.asarray(ids)
        max_nnz = sum(
            max(0, ids.shape[1] - o + 1) for o in self.orders
        ) or 1
        with _x64_if_needed(self.base, self.orders):
            indices, values = _vectorize(
                ids,
                jnp.asarray(lengths),
                self.keys_sorted,
                self.feat_of_pos,
                self.orders,
                self.base,
                self.weight,
                max_nnz,
            )
        return SparseBatch(
            indices=indices, values=values, num_features=self.num_features
        )

    def apply_batch(self, batch) -> SparseBatch:
        ids, lengths = batch
        return self.apply_encoded(ids, lengths)

    def apply(self, item) -> SparseBatch:
        ids, lengths = item
        return self.apply_encoded(ids, lengths)


class DeviceCommonSparseFeatures(Estimator):
    """Fused estimator for the reference text chain, on device (module doc).

    Consumes *encoded* padded id batches (``ids [D, L]`` int32 with pad/OOV
    -1 + ``lengths [D]``) — the output of
    ``WordFrequencyTransformer.encode_padded`` or a device-side synthetic
    generator. ``base`` must be ``vocab_size + 1`` (the host fast path's
    packing base). One host sync per fit (the distinct-key count, which
    fixes the static feature-table size).
    """

    def __init__(
        self,
        base: int,
        orders: Tuple[int, ...] = (1, 2),
        num_features: int = 100000,
        weight: str = "binary",
        mesh=None,
        mesh_axis: str = "data",
        shard_capacity: Optional[int] = None,
    ):
        if weight not in _WEIGHTS:
            raise ValueError(f"weight must be one of {_WEIGHTS}, got {weight!r}")
        orders = tuple(orders)
        if not orders or min(orders) < 1:
            raise ValueError(f"orders must be >= 1, got {orders}")
        _key_dtype(int(base), orders)  # raise OverflowError early
        self.base = int(base)
        self.orders = orders
        self.num_features = int(num_features)
        self.weight = weight
        # mesh with >1 device on mesh_axis -> document-sharded fit
        # (_fit_totals_sharded); tables identical to the single-device fit
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.shard_capacity = shard_capacity

    def fit(self, ids, lengths) -> DeviceNGramVectorizer:
        ids = jnp.asarray(ids)
        lengths = jnp.asarray(lengths)
        with _x64_if_needed(self.base, self.orders):
            if self.mesh is not None and self.mesh.shape[self.mesh_axis] > 1:
                distinct, totals, n_keys, over = _fit_totals_sharded(
                    ids, lengths, self.orders, self.base, self.weight,
                    self.mesh, self.mesh_axis, self.shard_capacity,
                )
                from keystone_tpu.ops.nlp.device_count import (
                    check_shard_capacity,
                )

                check_shard_capacity(over, self.shard_capacity)
            else:
                distinct, totals, n_keys = _fit_totals(
                    ids, lengths, self.orders, self.base, self.weight
                )
            k = min(self.num_features, int(n_keys))  # the fit's one host sync
            keys_sorted, feat_of_pos = _select_top_k(distinct, totals, max(k, 1))
        return DeviceNGramVectorizer(
            keys_sorted=keys_sorted,
            feat_of_pos=feat_of_pos,
            base=self.base,
            orders=self.orders,
            weight=self.weight,
        )

    def fit_transform(self, ids, lengths) -> Tuple[DeviceNGramVectorizer, SparseBatch]:
        vec = self.fit(ids, lengths)
        return vec, vec.apply_encoded(ids, lengths)
