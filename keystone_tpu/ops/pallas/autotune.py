"""Device-keyed empirical tile autotuner for the Pallas kernel family.

The TVM matmul-generator result (PAPERS.md, "Automatic Generators for a
Family of Matrix Multiplication Routines with Apache TVM") and the tile-shape
sensitivity documented for TPU matmuls in "Large Scale Distributed Linear
Algebra With Tensor Processing Units" both say the same thing: the right
tile shape is an *empirical* property of (kernel, device generation, problem
shape), not something a heuristic gets right across generations. This module
is the single tile-resolution path for every Pallas kernel in the package
(``ops/pallas/moments.py``, ``ops/pallas/extraction.py``) and for the
overlap schedulers' tile-count default
(``parallel/overlap.py::_pick_tiles``).

Model:

- Every tunable site is identified by a ``(kernel, device_key, bucket)``
  triple. ``device_key`` is backend + device generation
  (``"tpu:tpu_v5_lite"``, ``"cpu:cpu"``); ``bucket`` is the shape rounded
  up per-dimension to a power of two (:func:`shape_bucket`) so nearby
  shapes share one entry instead of re-sweeping per exact shape.
- :func:`resolve` is the one lookup path: a persisted winner is served
  immediately (``autotune.cache_hit``); on a miss the *declared default* is
  served (``autotune.default``) unless ``KEYSTONE_AUTOTUNE=1`` **and** the
  caller supplied a ``measure`` callback, in which case a bounded sweep
  runs (``autotune.sweep``), the winner is persisted, and subsequent
  resolutions — in this process or any later one on the same device
  generation — hit the cache with zero re-sweeps (pinned by
  ``tests/test_autotune.py`` via these counters).
- Sweeps are timed latency-cancelled exactly like
  ``scripts/bench_regime.py``: per candidate, (time of 1+R chained runs)
  − (time of 1), so the host↔device round-trip cancels and the difference
  is device time. The grid is bounded by ``KEYSTONE_AUTOTUNE_GRID``
  candidates and ``KEYSTONE_AUTOTUNE_BUDGET_S`` wall-clock seconds —
  exhaustion keeps the best-so-far, never blocks the caller.
- Winners persist in a device-keyed JSON cache
  (``autotune_cache.json`` at the repo root, next to
  ``lint_baseline.json``; ``KEYSTONE_AUTOTUNE_CACHE`` overrides the path).
  A corrupt or unwritable cache degrades to defaults with a warning —
  tuning is an optimization, never a correctness dependency.

The cache file format (``version`` guards future migrations)::

    {"version": 1,
     "devices": {
       "tpu:tpu_v5_lite": {
         "moments.tile_n": {"any":        {"value": 512, "us": 265.0, "swept": 3}},
         "sift.bins":      {"16384x256":  {"value": 256, "us": 81.2,  "swept": 4}},
         "overlap.tiles":  {"4096x8":     {"value": 8,   "us": 50.1,  "swept": 3}}}}}

``value`` is whatever the kernel tunes — a tile height for the row-tiled
kernels, a tile-count target for the overlap schedulers.

Bucket keys compose ``"<shape>[@tier][#variant]"``: the precision tier
(:func:`precision_bucket`) and, since the kernel-variant search
(``ops/pallas/variants.py``), a ``#<variant>`` suffix for non-default
generated kernel variants (``variants.variant_bucket``). The DEFAULT
variant of every kernel keeps the bare key, so pre-variant tile-only
entries remain valid winners; entries naming an unknown tier or variant
are pruned on load (:func:`_sanitize`), never served.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence

from keystone_tpu.utils import knobs
from keystone_tpu.utils.lockwitness import register_lock

_VERSION = 1
# RLock: record() calls _warn_once() (which takes the lock for the
# warned-set) while already holding it for the cache mutation.
_LOCK = register_lock(threading.RLock(), "autotune.cache")
# In-memory mirror of the cache file, keyed by the path it was loaded from
# so tests that repoint KEYSTONE_AUTOTUNE_CACHE get a fresh load.
_MEM: Optional[Dict[str, Any]] = None
_MEM_PATH: Optional[str] = None
_WARNED: set = set()


def _registry():
    from keystone_tpu.telemetry import get_registry

    return get_registry()


def _warn_once(key: str, msg: str) -> None:
    with _LOCK:
        if key in _WARNED:
            return
        _WARNED.add(key)
    print(f"autotune: {msg}", file=sys.stderr)


def device_key() -> str:
    """``backend:device_generation`` — the cache partition key. Tile winners
    transfer across chips of one generation but not across generations
    (v4 vs v5e have different VMEM/MXU balances), and never across
    backends."""
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", None) or dev.platform
    slug = re.sub(r"[^a-z0-9]+", "_", str(kind).lower()).strip("_")
    return f"{jax.default_backend()}:{slug}"


def shape_bucket(*dims: int) -> str:
    """Power-of-two bucket per dimension (``"16384x256"``): shapes within a
    2x band share one tuned entry, so ragged batch tails don't each trigger
    their own sweep."""
    parts = []
    for d in dims:
        d = int(d)
        parts.append(str(1 << max(0, (d - 1).bit_length()) if d > 0 else 0))
    return "x".join(parts)


#: storage dtype tiers a bucket key may be qualified with (the
#: KEYSTONE_PRECISION_TIER values; mirrors linalg.solvers.PRECISION_TIERS
#: without importing jax at module load)
KNOWN_TIERS = ("f32", "bf16")


def precision_bucket(bucket: str, tier: Optional[str] = None) -> str:
    """Precision joins tile shape in the cache key: a winner swept for
    bf16-stored operands must never serve an f32 call or vice versa — the
    two dtypes have different VMEM footprints, MXU pass counts and
    bandwidth balances, so their optimal tiles differ. ``"f32"``/None keeps
    the bare shape bucket (every pre-tier cache entry remains a valid f32
    winner); other tiers append ``@<tier>`` (``"16384x256@bf16"``).
    Unknown tiers raise — a typo'd tier silently creating its own cache
    partition would never be served."""
    if tier in (None, "f32"):
        return bucket
    if tier not in KNOWN_TIERS:
        raise ValueError(
            f"precision tier must be one of {KNOWN_TIERS}: {tier!r}"
        )
    return f"{bucket}@{tier}"


def _known_variant_spaces() -> Optional[Dict[str, Any]]:
    """The kernel-variant registry (``ops/pallas/variants.py``), imported
    LAZILY so sanitization always sees the fully-populated spaces (an
    import-time snapshot could prune valid ``#variant`` entries registered
    later). None when the registry is unavailable — in that case variant
    suffixes cannot be judged and are kept, never silently dropped."""
    try:
        from keystone_tpu.ops.pallas import variants

        return variants.VARIANT_SPACES
    except Exception:
        return None


def _bucket_key_ok(kernel: str, b: str) -> bool:
    """Whether one bucket key names a (tier, variant) this build speaks.
    Keys read ``"<shape>[@tier][#variant]"`` — the variant suffix joins
    LAST (``variants.variant_bucket`` composes over ``precision_bucket``).
    A precision tier outside :data:`KNOWN_TIERS` or a ``#variant`` not in
    the kernel's declared space (hand edit, future format, renamed
    variant) is stale and must not shadow — or be mistaken for — a real
    winner. Default variants never carry a suffix, so every pre-variant
    tile-only key passes unchanged."""
    base, sep, var = b.partition("#")
    if "@" in base and base.rsplit("@", 1)[1] not in KNOWN_TIERS:
        return False
    if not sep:
        return True
    spaces = _known_variant_spaces()
    if spaces is None:  # registry unavailable: keep rather than destroy
        return True
    space = spaces.get(kernel)
    return bool(space) and var in space


def cache_path() -> str:
    """``KEYSTONE_AUTOTUNE_CACHE`` when set, else ``autotune_cache.json`` at
    the repo root (next to ``lint_baseline.json`` — same ratchet-artifact
    neighborhood)."""
    override = knobs.get("KEYSTONE_AUTOTUNE_CACHE")
    if override:
        return override
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    )
    return os.path.join(root, "autotune_cache.json")


def _sanitize(raw: Any) -> Optional[Dict[str, Any]]:
    """Deep-validate a parsed cache file into the canonical shape, pruning
    malformed branches (hand edits, foreign versions). Returns None when
    the top level itself is unusable. Every read goes through this one
    choke point, so downstream code can assume the nesting — tuning must
    never become a correctness dependency via a crash on a bad file."""
    if (
        not isinstance(raw, dict)
        or raw.get("version") != _VERSION
        or not isinstance(raw.get("devices"), dict)
    ):
        return None
    devices: Dict[str, Any] = {}
    pruned = False
    for dev, kernels in raw["devices"].items():
        if not isinstance(kernels, dict):
            pruned = True
            continue
        dev_out: Dict[str, Any] = {}
        for kname, buckets in kernels.items():
            if not isinstance(buckets, dict):
                pruned = True
                continue
            good = {
                b: e for b, e in buckets.items()
                if isinstance(e, dict) and "value" in e
                and _bucket_key_ok(str(kname), b)
            }
            pruned = pruned or len(good) != len(buckets)
            if good:
                dev_out[str(kname)] = good
        if dev_out:
            devices[str(dev)] = dev_out
    if pruned:
        _warn_once(
            "sanitize", "cache held malformed entries; they were ignored"
        )
    return {"version": _VERSION, "devices": devices}


def _load_locked(path: str) -> Dict[str, Any]:
    """Load (or re-load) the cache file into the in-memory mirror. Caller
    holds ``_LOCK``."""
    global _MEM, _MEM_PATH
    if _MEM is not None and _MEM_PATH == path:
        return _MEM
    data: Optional[Dict[str, Any]] = None
    try:
        with open(path) as f:
            data = _sanitize(json.load(f))
        if data is None:
            _warn_once(
                f"schema:{path}",
                f"ignoring {path}: unrecognized schema "
                f"(expected version={_VERSION}) — starting fresh",
            )
    except FileNotFoundError:
        pass
    except (OSError, ValueError) as e:
        _warn_once(
            f"load:{path}", f"ignoring unreadable cache {path}: {e}"
        )
    if data is None:
        data = {"version": _VERSION, "devices": {}}
    _MEM, _MEM_PATH = data, path
    return data


def clear_memory_cache() -> None:
    """Drop the in-memory mirror so the next lookup re-reads the file —
    test hook for pinning the persisted (not in-process) round trip."""
    global _MEM, _MEM_PATH
    with _LOCK:
        _MEM = None
        _MEM_PATH = None


def _peek(kernel: str, bucket: str) -> Optional[Any]:
    """The persisted winner, without touching any counter — the internal
    read :func:`lookup` and :func:`resolve` both build on, so each can
    report exactly ONE outcome for a resolution."""
    path = cache_path()
    with _LOCK:
        data = _load_locked(path)
        entry = (
            data["devices"].get(device_key(), {}).get(kernel, {}).get(bucket)
        )
    return None if entry is None else entry.get("value")


def peek_entry(kernel: str, bucket: str) -> Optional[Dict[str, Any]]:
    """The FULL persisted entry (``{"value", "us", "swept"}``) for
    ``(kernel, device_key(), bucket)``, or None — no counters, no sweeps.
    The variant search (``ops/pallas/variants.py``) arbitrates winners on
    the persisted ``us`` latencies, which :func:`lookup`'s value-only
    contract cannot expose."""
    path = cache_path()
    with _LOCK:
        data = _load_locked(path)
        entry = (
            data["devices"].get(device_key(), {}).get(kernel, {}).get(bucket)
        )
    return None if entry is None else dict(entry)


def lookup(kernel: str, bucket: str) -> Optional[Any]:
    """The persisted winner for ``(kernel, device_key(), bucket)``, or None.

    Pure lookup — never sweeps, never writes; safe to call from eager
    wrappers on every invocation (the mirror is one dict access) and from
    non-Pallas consumers like ``overlap._pick_tiles``. Counts
    ``autotune.cache_hit`` / ``autotune.cache_miss`` per call."""
    value = _peek(kernel, bucket)
    if value is None:
        _registry().inc("autotune.cache_miss", kernel=kernel)
        return None
    _registry().inc("autotune.cache_hit", kernel=kernel)
    return value


def record(
    kernel: str,
    bucket: str,
    value: Any,
    micros: Optional[float] = None,
    swept: int = 0,
) -> None:
    """Persist a winner (atomic tmp+rename). An unwritable cache directory
    degrades to in-memory-only with a warning — the winner still serves
    this process.

    The write merges against a FRESH read of the file under an exclusive
    ``flock`` on a sidecar lockfile, not this process's mirror: two
    PROCESSES sweeping different kernels concurrently (bench subprocesses,
    multi-host pod runs sharing a filesystem) must not clobber each
    other's entries — an entry lost to a stale rewrite would be re-swept
    on the next run, breaking the zero-re-sweeps contract. (The in-process
    ``_LOCK`` only serializes threads; the flock covers the
    read→merge→replace window across processes. Filesystems without flock
    degrade to best-effort.)"""
    global _MEM, _MEM_PATH
    path = cache_path()
    # The flock sidecar is created LAZILY, here and only here (the first
    # actual write), and only when the cache directory already exists —
    # an unwritable/missing dir must not grow a dangling ``.lock`` while
    # the entry itself degrades to in-memory-only. The sidecar is a local
    # artifact: gitignored, never committed (it used to be).
    lockf = None
    try:
        if os.path.isdir(os.path.dirname(os.path.abspath(path))):
            import fcntl

            lockf = open(f"{path}.lock", "w")
            fcntl.flock(lockf, fcntl.LOCK_EX)
    except Exception:
        if lockf is not None:
            lockf.close()
            lockf = None
    with _LOCK:
        mem = _load_locked(path)
        _MEM = None  # force a fresh disk read under the lock
        _MEM_PATH = None
        data = _load_locked(path)
        # keep this process's in-memory-only winners (e.g. earlier writes
        # that failed on an unwritable dir) where the disk has no entry
        for dev, kernels in mem["devices"].items():
            for kname, buckets in kernels.items():
                for b, e in buckets.items():
                    data["devices"].setdefault(dev, {}).setdefault(
                        kname, {}
                    ).setdefault(b, e)
        entry: Dict[str, Any] = {"value": value, "swept": int(swept)}
        if micros is not None:
            entry["us"] = round(float(micros), 2)
        data["devices"].setdefault(device_key(), {}).setdefault(kernel, {})[
            bucket
        ] = entry
        _MEM, _MEM_PATH = data, path
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        except OSError as e:
            _warn_once(
                f"write:{path}",
                f"cache not persisted to {path} ({e}); winners serve "
                "this process only",
            )
        finally:
            if lockf is not None:
                lockf.close()  # drops the flock


def chained_measure(
    build: Callable[[Any], Callable[[int], Any]],
) -> Callable[[Any, int], float]:
    """The one timing protocol every kernel's sweep uses (finding of the
    review pass: four call sites had hand-copied it). ``build(candidate)``
    returns ``run(i)`` — one dispatch of the kernel at that candidate,
    varied by ``i`` so chained dispatches cannot collapse into a cached
    value. The returned ``measure(candidate, reps)`` warms the compile
    with one synced run, then times ``reps`` chained dispatches ending in
    a single sync — the form :func:`sweep`'s latency cancellation
    expects."""
    import time

    import jax

    def measure(candidate, reps: int) -> float:
        run = build(candidate)
        jax.block_until_ready(run(-1))  # warm compile outside the timing
        t0 = time.perf_counter()
        out = None
        for i in range(reps):
            out = run(i)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    return measure


def sweep(
    kernel: str,
    bucket: str,
    candidates: Sequence[Any],
    measure: Callable[[Any, int], float],
    reps: int = 3,
) -> Any:
    """Bounded empirical sweep; returns the winner and persists it.

    ``measure(candidate, k)`` runs k chained executions of the kernel at
    ``candidate`` and returns elapsed seconds (including the final sync);
    per candidate the score is ``(measure(1+reps) - measure(1)) / reps`` —
    the latency-cancelled device time of one run
    (``bench_regime._latency_cancelled_gflops``'s form). A candidate that
    raises (e.g. a tile the shape cannot support) is skipped, not fatal.
    The grid is truncated to ``KEYSTONE_AUTOTUNE_GRID`` entries and the
    sweep stops early once ``KEYSTONE_AUTOTUNE_BUDGET_S`` wall-clock
    seconds are spent — best-so-far still wins and is persisted."""
    grid = list(candidates)[: max(1, knobs.get("KEYSTONE_AUTOTUNE_GRID"))]
    budget_s = knobs.get("KEYSTONE_AUTOTUNE_BUDGET_S")
    # lint: disable=R1 (this IS the timing harness: sweeps run eagerly by
    # contract — resolve() refuses to sweep without a measure callback, and
    # callers only pass one from eager wrappers)
    t0 = time.monotonic()
    best, best_dt, tried = None, None, 0
    for cand in grid:
        # lint: disable=R1 (budget clock of the eager sweep harness)
        if tried and time.monotonic() - t0 > budget_s:
            _warn_once(
                f"budget:{kernel}:{bucket}",
                f"{kernel}[{bucket}]: sweep budget {budget_s}s exhausted "
                f"after {tried}/{len(grid)} candidates",
            )
            break
        try:
            t1 = measure(cand, 1)
            tn = measure(cand, 1 + reps)
            dt = (tn - t1) / reps
            if dt <= 0:  # timing noise: fall back to the mean-per-run form
                dt = tn / (1 + reps)
        except Exception as e:
            _warn_once(
                f"cand:{kernel}:{bucket}:{cand}",
                f"{kernel}[{bucket}]: candidate {cand!r} failed "
                f"({type(e).__name__}: {e}); skipped",
            )
            continue
        tried += 1
        if best_dt is None or dt < best_dt:
            best, best_dt = cand, dt
    if best is None:
        # no counter here: resolve() falls through to the default path,
        # which fires the single outcome counter for this resolution
        _warn_once(
            f"empty:{kernel}:{bucket}",
            f"{kernel}[{bucket}]: every candidate failed; keeping default",
        )
        return None
    _registry().inc("autotune.sweep", kernel=kernel)
    record(
        kernel, bucket, best,
        micros=best_dt * 1e6 if best_dt else None, swept=tried,
    )
    return best


def resolve(
    kernel: str,
    bucket: str,
    candidates: Sequence[Any],
    default: Any,
    measure: Optional[Callable[[Any, int], float]] = None,
) -> Any:
    """The one tile-resolution path every Pallas kernel uses.

    Persisted winner → served (``autotune.cache_hit``), but only when it
    is still in this call's ``candidates``: callers constrain candidates
    by the ACTUAL shape (VMEM fit bounds), and shapes within one pow2
    bucket differ up to 2x per dim — a winner swept at the small end of a
    bucket may overflow VMEM at the large end, so an out-of-grid hit is
    treated as a miss rather than served. Miss with ``KEYSTONE_AUTOTUNE=1``
    and a ``measure`` callback → sweep once, persist, serve. Miss
    otherwise → the declared ``default`` (``autotune.default``). Must be
    called from EAGER wrappers only — the result feeds jit-static block
    shapes, and a sweep times real executions. Exactly ONE outcome counter
    fires per resolution: ``cache_hit``, ``sweep`` (inside :func:`sweep`),
    or ``default`` — a rejected out-of-grid winner counts as whatever path
    actually served."""
    hit = _peek(kernel, bucket)
    if hit is not None and (not candidates or hit in candidates):
        _registry().inc("autotune.cache_hit", kernel=kernel)
        return hit
    if measure is not None and knobs.get("KEYSTONE_AUTOTUNE"):
        won = sweep(kernel, bucket, candidates, measure)
        if won is not None:
            return won
    _registry().inc("autotune.default", kernel=kernel)
    return default
