"""Kernel variant registry + search for the generated extraction kernels.

PR-7 gave every extraction kernel ONE hand-written form and let the
autotuner pick its tile. The TVM matmul-generator result (PAPERS.md,
"Automatic Generators for a Family of Matrix Multiplication Routines with
Apache TVM") says the bigger win is searching over *generated kernel
variants* — loop order, block mapping, fusion span — with the same
measured-winner discipline. This module is that layer: each kernel in
``ops/pallas/extraction.py`` declares a small variant space (the first
name is always the pre-variant hand-written form), the autotuner's cache
grows a ``#<variant>`` bucket suffix for non-default variants (the default
keeps the BARE bucket, so every pre-variant tile-only entry remains a
valid winner), and :func:`search` arbitrates: per variant the tile is
resolved through ``autotune.resolve`` at the variant-qualified bucket, and
the cross-variant winner is the entry with the smallest persisted ``us``.

The safety net (a generated kernel can win on speed, never on wrong
answers): before a non-default variant's FIRST sweep it must pass
:func:`validate_variant` — bit-envelope parity against the reference form
plus the A1/A4 ``ir_rules`` checks (no collectives in a single-device
extraction program; no gross MXU-tile padding waste) on its lowered
program. A variant that fails is never swept, never recorded, never
served (``variants.rejected`` counts it); an entry someone hand-edits into
the cache under an UNKNOWN variant name is pruned by ``autotune._sanitize``
on load.

Variant spaces (the table the README mirrors):

==========  ==========================  =====================================
kernel      variants (default first)    what varies
==========  ==========================  =====================================
sift.bins   unroll | stack              per-bin loop of 8 small matmuls vs
                                        one stacked (8·TR, W) matmul
fv.encode   pair | joint                two (Kp, d) moment matmuls vs one
                                        (Kp, 2d) matmul on concat [x, x²]
conv.norm   yx | xy                     k² shifted-matmul accumulation order
                                        (dy-outer vs dx-outer)
pool.sum    hw | wh                     separable contraction order (H-axis
                                        first vs W-axis first)
conv.pool   split | fused.yx|fused.xy   fusion span: conv.norm→HBM→pool.sum
                                        vs one kernel holding the convolved
                                        patch block VMEM-resident through
                                        normalization AND pooling
==========  ==========================  =====================================

The bf16-input vs f32 streaming axis is NOT a variant name — it is the
existing precision-tier bucket qualifier (``@bf16``), orthogonal to the
variant suffix: a full key reads ``"<shape>[@tier][#variant]"``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from keystone_tpu.ops.pallas import autotune
from keystone_tpu.utils import knobs

#: kernel -> variant names; index 0 is the DEFAULT (the pre-variant
#: hand-written form, cached under the bare bucket key). ``autotune.
#: _sanitize`` prunes cache entries whose ``#<variant>`` suffix is not
#: listed here — an unknown variant must never shadow or serve.
VARIANT_SPACES: Dict[str, Tuple[str, ...]] = {
    "sift.bins": ("unroll", "stack"),
    "fv.encode": ("pair", "joint"),
    "conv.norm": ("yx", "xy"),
    "pool.sum": ("hw", "wh"),
    "conv.pool": ("split", "fused.yx", "fused.xy"),
}

#: default rel tolerance of the bit-envelope parity gate per storage tier
#: (mirrors the parity-test envelopes: f32 interpret-mode reassociation
#: noise vs bf16 storage rounding)
PARITY_TOL = {"f32": 2e-5, "bf16": 2e-2}


def _count(event: str, **labels) -> None:
    from keystone_tpu.telemetry import get_registry

    get_registry().inc(f"variants.{event}", **labels)


def known_variants(kernel: str) -> Tuple[str, ...]:
    """The kernel's declared variant space (default first). Unknown
    kernels raise — a typo'd kernel name silently creating its own space
    would never be searched."""
    try:
        return VARIANT_SPACES[kernel]
    except KeyError:
        raise ValueError(
            f"no variant space declared for kernel {kernel!r}"
        ) from None


def default_variant(kernel: str) -> str:
    return known_variants(kernel)[0]


def variant_bucket(bucket: str, kernel: str, variant: str) -> str:
    """Variant joins the cache key AFTER the precision tier:
    ``"<shape>[@tier][#variant]"``. The default variant keeps the bare
    bucket — every pre-variant tile-only cache entry stays a valid winner
    for it — and unknown variants raise (same contract as
    ``autotune.precision_bucket``: a typo must not mint a partition)."""
    space = known_variants(kernel)
    if variant not in space:
        raise ValueError(
            f"unknown {kernel} variant {variant!r} (known: {space})"
        )
    if variant == space[0]:
        return bucket
    return f"{bucket}#{variant}"


# ---------------------------------------------------------------------------
# The safety net: parity + program-shape checks before a variant may sweep
# ---------------------------------------------------------------------------


def _max_rel_err(got, want) -> float:
    import jax
    import numpy as np

    errs = [0.0]
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        denom = float(np.max(np.abs(b))) + 1e-9
        errs.append(float(np.max(np.abs(a - b))) / denom)
    # np.max propagates NaN (Python's max() would silently drop it, and a
    # NaN-producing variant must fail the gate, not slip past it)
    return float(np.max(errs))


def check_program(fn: Callable, *args) -> list:
    """The A1/A4 ``ir_rules`` shape of one candidate program: extraction
    kernels are single-device, so ANY collective is a finding (A1 family),
    and matmul operand dims must not waste the MXU tile past the audit
    threshold (A4). Returns the list of problems (empty = clean)."""
    import jax

    from keystone_tpu.analysis import ir_rules

    problems = list(ir_rules.padded_matmul_dims(jax.make_jaxpr(fn)(*args)))
    hlo = jax.jit(fn).lower(*args).compile().as_text()
    problems += ir_rules.check_no_all_reduce(hlo)
    problems += ir_rules.check_no_bulk_collectives(hlo)
    return problems


def validate_variant(
    kernel: str,
    variant: str,
    run: Callable[[], Any],
    run_reference: Callable[[], Any],
    *,
    tol: float,
    program: Optional[Callable] = None,
    program_args: Sequence[Any] = (),
) -> bool:
    """The gate between "generated" and "allowed to sweep": bit-envelope
    parity of ``run()`` against ``run_reference()`` (max-normalized rel
    error <= ``tol``) plus :func:`check_program` on the variant's lowered
    form when ``program`` is given. A failing variant is counted
    (``variants.rejected{kernel,variant,reason}``) and must never be
    recorded or served; a passing one counts ``variants.validated``."""
    try:
        err = _max_rel_err(run(), run_reference())
    except Exception as e:  # a variant that cannot even run is rejected
        _count("rejected", kernel=kernel, variant=variant,
               reason=type(e).__name__)
        return False
    if not err <= tol:  # NaN-safe: NaN comparisons are False
        _count("rejected", kernel=kernel, variant=variant, reason="parity")
        return False
    if program is not None:
        try:
            problems = check_program(program, *program_args)
        except Exception as e:
            _count("rejected", kernel=kernel, variant=variant,
                   reason=type(e).__name__)
            return False
        if problems:
            _count("rejected", kernel=kernel, variant=variant,
                   reason="ir_rules")
            return False
    _count("validated", kernel=kernel, variant=variant)
    return True


# ---------------------------------------------------------------------------
# The search driver
# ---------------------------------------------------------------------------


def search(
    kernel: str,
    bucket: str,
    candidates: Sequence[Any],
    default: Any,
    *,
    measure_for: Optional[Callable[[str], Callable[[Any, int], float]]] = None,
    validate_for: Optional[Callable[[str], bool]] = None,
    allow_sweep: bool = True,
) -> Tuple[str, Any]:
    """Variant-space resolution on top of ``autotune.resolve``; returns
    ``(variant, value)``.

    The default variant rides the existing single-kernel path at the bare
    bucket (sweeping under ``KEYSTONE_AUTOTUNE=1`` exactly as before).
    Non-default variants resolve at their ``#``-qualified buckets:
    persisted entries serve lookup-only like any tile winner; a MISSING
    entry is swept only when ``KEYSTONE_AUTOTUNE=1`` AND
    ``KEYSTONE_AUTOTUNE_VARIANTS`` is on AND the variant first passes
    ``validate_for`` (the parity + ir_rules gate) — so after one full
    sweep a reload performs ZERO re-sweeps, the same contract tiles pin.

    Winner selection is the measured-winner protocol ACROSS variants: a
    challenger is served only when both it and the default carry a
    persisted latency (``us``) and the challenger's is strictly smaller —
    a variant can win on measured speed, never by default. Out-of-grid
    values (a winner swept at the small end of a pow2 bucket that no
    longer fits this shape's candidates) are skipped, mirroring
    ``resolve``'s own guard."""
    space = known_variants(kernel)
    dflt = space[0]
    sweep_ok = bool(
        allow_sweep and measure_for is not None
        and knobs.get("KEYSTONE_AUTOTUNE")
    )
    variants_ok = sweep_ok and knobs.get("KEYSTONE_AUTOTUNE_VARIANTS")
    value = autotune.resolve(
        kernel, bucket, candidates, default,
        measure=measure_for(dflt) if sweep_ok else None,
    )
    base = autotune.peek_entry(kernel, bucket)
    base_us = None if base is None else base.get("us")
    if base_us is None:
        # no measured incumbent: nothing to beat, the default serves
        return dflt, value
    best_name, best_value, best_us = dflt, value, float(base_us)
    for name in space[1:]:
        vb = variant_bucket(bucket, kernel, name)
        entry = autotune.peek_entry(kernel, vb)
        if entry is None and variants_ok:
            if validate_for is None or validate_for(name):
                autotune.resolve(
                    kernel, vb, candidates, default,
                    measure=measure_for(name),
                )
                entry = autotune.peek_entry(kernel, vb)
        if entry is None:
            continue
        v, us = entry.get("value"), entry.get("us")
        if us is None or (candidates and v not in candidates):
            continue
        if float(us) < best_us:
            best_name, best_value, best_us = name, v, float(us)
    if best_name != dflt:
        _count("selected", kernel=kernel, variant=best_name)
    return best_name, best_value
