"""Fused GMM posterior-moment accumulation as a Pallas TPU kernel.

This is the shared hot loop under both GMM-EM (M-step sufficient statistics,
``learning/gmm.py``) and Fisher Vector encoding (``ops/images/
fisher_vector.py``) — the TPU-native replacement for the enceval C++ EM and
FV encoders (reference ``src/main/cpp/EncEval.cxx:122-180`` and ``:19-120``).

Why a kernel: a naive XLA formulation materializes the (n, k)
responsibility matrix in HBM between the E-step softmax and the M-step
matmuls. At the reference's flagship scale (1e7 samples × 256 centers,
``ImageNetSiftLcsFV.scala:197-218``) that intermediate alone is 10 GB —
beyond HBM — and even when it fits, it costs two full HBM round-trips. In
the Pallas kernel each row tile is streamed HBM→VMEM once; the log-density
(two MXU matmuls), the softmax, and the three weighted-moment accumulations
all happen in VMEM, and only the (k, d)-shaped accumulators ever leave the
chip. HBM traffic drops from O(n·k + n·d) to O(n·d).

Math: with per-component affine parameters precomputed host-side,

    ll = x @ A + x² @ B + c,   A = (μ/σ²)ᵀ,  B = (−½/σ²)ᵀ,
    c  = log w − ½(d·log 2π + Σ log σ²) − ½ Σ μ²/σ²

so the E-step is itself MXU-shaped. The expansion loses precision when
``|x|`` is large (x² terms cancel), so every path first subtracts a
``center`` vector from x and μ — the log-density is shift-invariant, and
the returned moments are shifted back in closed form (``_uncenter``), which
is exact. Two trailing columns appended to x — the per-row weight (0 for
padding rows; scales q in-kernel) and a constant 1 — make ``qsum = Σ w·q``
fall out of the same ``qᵀx`` matmul as the ones column: no separate
reduction, and row masking is free. A/B rows for padded feature columns are
zero, so padding never perturbs the log-density.

Entry points: :func:`gmm_moments_sep` (the copy-free Pallas kernel —
separate weight/center operands, no padded input copy; the measured winner
at the design point), :func:`gmm_moments` (the augmented-layout kernel the
EM loop hoists via :func:`augment_rows` + :func:`moments_from_aug` — its
lane-padded input copy makes it unsuitable for huge one-shot calls),
:func:`gmm_moments_xla` (single fused XLA program, same affine math, any
backend), and :func:`gmm_moments_auto` (the default used by GMM-EM and
Fisher Vectors: XLA small, Pallas-sep large-on-TPU, scan-of-XLA-chunks
large-elsewhere; measured numbers in its docstring).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-tile height default: multiple of the f32 sublane (8); 512 amortizes
# the matmul well while keeping the q tile (512×k_pad) comfortably in VMEM.
# The ACTUAL tile is resolved through the shared device-keyed autotuner
# (:func:`_tile_n` -> ``ops/pallas/autotune.py``) so a swept winner for this
# device generation beats the hard-coded default.
_TILE_N_DEFAULT = 512
_TILE_N_CANDIDATES = (256, 512, 1024)
_LANE = 128
_SUBLANE = 8


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _tile_n(measure=None, tier: str = "f32") -> int:
    """Row-tile height via the shared tile-resolution path. Lookup-only by
    default (``moments_from_aug`` runs inside the jitted EM loop — a sweep
    there would time kernels at trace time); the eager one-shot entry
    (:func:`gmm_moments_sep`) passes a ``measure`` so ``KEYSTONE_AUTOTUNE=1``
    sweeps once and persists. Bucket is ``"any"``: the winning row tile is a
    device-generation property (VMEM/MXU balance), not a shape property —
    and a single value keeps :func:`augment_rows` padding and the kernel
    grid consistent by construction. The precision tier qualifies the
    bucket (``"any@bf16"``) — bf16 tiles hold twice the rows per VMEM byte,
    so the two tiers tune independently."""
    from keystone_tpu.ops.pallas import autotune

    return int(autotune.resolve(
        "moments.tile_n", autotune.precision_bucket("any", tier),
        _TILE_N_CANDIDATES, _TILE_N_DEFAULT, measure=measure,
    ))


def _fit_tile(n_pad: int, tile: int) -> int:
    """Largest power-of-two halving of ``tile`` dividing ``n_pad`` — guards
    the augmented kernel's exact grid when the sample was padded under a
    different (older/smaller) persisted tile than the current resolution."""
    while tile > _SUBLANE and n_pad % tile:
        tile //= 2
    return max(tile, _SUBLANE)


def _moments_kernel(x_ref, a_ref, b_ref, c_ref, qx_ref, qx2_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        qx_ref[:] = jnp.zeros_like(qx_ref)
        qx2_ref[:] = jnp.zeros_like(qx2_ref)

    x = x_ref[:]  # (T, D) — column D-2 holds the row weight, D-1 ones
    x2 = x * x
    ll = (
        jnp.dot(x, a_ref[:], preferred_element_type=jnp.float32)
        + jnp.dot(x2, b_ref[:], preferred_element_type=jnp.float32)
        + c_ref[:]
    )  # (T, K); padded centers carry c = -1e30 -> softmax ~ 0
    m = jnp.max(ll, axis=1, keepdims=True)
    e = jnp.exp(ll - m)
    q = e / jnp.sum(e, axis=1, keepdims=True)

    w_col = a_ref.shape[0] - 2  # weight column index (static)
    q = q * x[:, w_col][:, None]  # row weights; 0 for padding rows

    qt = q.T  # (K, T)
    qx_ref[:] += jnp.dot(qt, x, preferred_element_type=jnp.float32)
    qx2_ref[:] += jnp.dot(qt, x2, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def _moments_pallas(x_aug, A, B, c, *, tile_n: int, interpret: bool):
    n_pad, d_pad = x_aug.shape
    k_pad = A.shape[1]
    grid = (n_pad // tile_n,)
    qx, qx2 = pl.pallas_call(
        _moments_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
        ],
        interpret=interpret,
    )(x_aug, A, B, c)
    return qx, qx2


def _moments_kernel_sep(
    x_ref, w_ref, ctr_ref, a_ref, b_ref, c_ref, qsum_ref, qx_ref, qx2_ref,
    *, n_rows: int
):
    """Separate-input kernel: raw x tile + (T, 1) row weights + (1, D)
    center. Centering happens in VMEM (``x - center`` never exists in HBM)
    and the row-weight/ones columns of the augmented layout become their own
    tiny operands — so unlike :func:`_moments_kernel` there is NO padded
    (n, round_up(d+2, 128)) copy of the input. For the flagship moments
    regime (1e7×256, d=64) that copy alone (5.1 GB next to the 2.6 GB
    input) pushed the augmented kernel out of HBM.

    ``n_rows`` is the true (unpadded) row count, static at trace time: the
    grid ceil-divides n, the final tile's out-of-bounds lanes read garbage,
    and this mask zeroes both x and w there — so ragged n costs one VPU
    compare+select per tile instead of an ``x[:n_main]`` device copy."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        qsum_ref[:] = jnp.zeros_like(qsum_ref)
        qx_ref[:] = jnp.zeros_like(qx_ref)
        qx2_ref[:] = jnp.zeros_like(qx2_ref)

    tile_n = x_ref.shape[0]
    row_ids = i * tile_n + jax.lax.broadcasted_iota(
        jnp.int32, (tile_n, 1), 0
    )
    valid = row_ids < n_rows  # (T, 1); False only in the final ragged tile
    # bf16-input variant: the x tile streams HBM→VMEM in bfloat16 under
    # KEYSTONE_PRECISION_TIER=bf16 and upcasts here; centering, the
    # log-density matmuls and the moment accumulators all stay f32 (no-op
    # astype on the f32 tier — byte-identical prior kernel).
    x = jnp.where(
        valid, x_ref[:].astype(jnp.float32) - ctr_ref[:], 0.0
    )  # (T, D) centered
    x2 = x * x
    ll = (
        jnp.dot(x, a_ref[:], preferred_element_type=jnp.float32)
        + jnp.dot(x2, b_ref[:], preferred_element_type=jnp.float32)
        + c_ref[:]
    )  # (T, K); padded centers carry c = -1e30 -> softmax ~ 0
    m = jnp.max(ll, axis=1, keepdims=True)
    e = jnp.exp(ll - m)
    q = e / jnp.sum(e, axis=1, keepdims=True)
    w = jnp.where(valid, w_ref[:], 0.0)
    q = q * w  # (T, 1) row weights; 0 for padding / out-of-bounds rows

    qsum_ref[:] += jnp.sum(q, axis=0, keepdims=True)
    qt = q.T  # (K, T)
    qx_ref[:] += jnp.dot(qt, x, preferred_element_type=jnp.float32)
    qx2_ref[:] += jnp.dot(qt, x2, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def _moments_pallas_sep(x, w, center, A, B, c, *, tile_n: int, interpret: bool):
    n, d_pad = x.shape
    k_pad = A.shape[1]
    grid = (pl.cdiv(n, tile_n),)
    qsum, qx, qx2 = pl.pallas_call(
        functools.partial(_moments_kernel_sep, n_rows=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, center, A, B, c)
    return qsum, qx, qx2


def gmm_moments_sep(
    x: jax.Array,
    means: jax.Array,
    variances: jax.Array,
    weights: jax.Array,
    row_weights: Optional[jax.Array] = None,
    *,
    center: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    tier: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`gmm_moments` through the copy-free separate-input kernel.

    The only per-n allocation beyond x itself is the (n, 1) row-weight
    column — the kernel that actually holds the module docstring's
    O(n·d)-traffic promise at the design point (the augmented kernel pays
    an extra lane-padded input copy, fatal at 1e7×64 on a 16 GB chip).
    Ragged n is handled by the kernel's in-tile row mask (the grid
    ceil-divides n and x is consumed whole), so at n=1e7 — where
    1e7 % 512 = 128 — no near-full slice copy of x is ever materialized.

    ``tier`` (None = the ``KEYSTONE_PRECISION_TIER`` knob, resolved here
    eagerly): ``"bf16"`` hands the kernel a bfloat16-stored x — HALF the
    O(n·d) HBM traffic this kernel exists to minimize — with centering and
    all moment accumulation still f32 in VMEM. The center statistic itself
    is computed from the f32 input before the cast. The small-n XLA
    fallbacks below ignore the tier (no bandwidth to save there).
    """
    from keystone_tpu.linalg.solvers import resolve_precision_tier

    tier = resolve_precision_tier(tier)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    x = jnp.asarray(x, jnp.float32)
    n, d = x.shape
    if center is None:
        center = jnp.mean(x, axis=0)
    k = means.shape[0]
    k_pad = _round_up(k, _LANE)
    if n < min(_TILE_N_CANDIDATES):
        # A single sub-tile call gains nothing from Pallas; one small XLA
        # program is cheaper than a one-tile kernel launch.
        return gmm_moments_xla(x, means, variances, weights, row_weights,
                               center)
    w = jnp.ones((n,), jnp.float32) if row_weights is None else row_weights
    w = w.reshape(n, 1).astype(jnp.float32)
    A, B, c = _prep_params(
        jnp.asarray(means, jnp.float32) - center[None],
        jnp.asarray(variances, jnp.float32),
        jnp.asarray(weights, jnp.float32),
        d,
        k_pad,
    )
    ctr = center.reshape(1, d)
    x32 = x
    if tier == "bf16":
        # storage cast AFTER the f32 center statistic; the kernel upcasts
        # per-tile in VMEM (x32 is kept un-cast for the XLA fallback below
        # — that path streams nothing, so it must not pay the rounding)
        x = x.astype(jnp.bfloat16)

    def _build(tile):
        # the sweep times THIS call's actual operands — the sweep is the
        # workload (only reached eagerly, on KEYSTONE_AUTOTUNE=1 + miss)
        return lambda i: _moments_pallas_sep(
            x, w, ctr, A, B, c, tile_n=int(tile), interpret=bool(interpret)
        )

    from keystone_tpu.ops.pallas import autotune as _autotune

    tile_n = _tile_n(measure=_autotune.chained_measure(_build), tier=tier)
    if n < tile_n:
        return gmm_moments_xla(x32, means, variances, weights, row_weights,
                               center)
    qsum_p, qxc, qxc2 = _moments_pallas_sep(
        x, w, ctr, A, B, c, tile_n=tile_n, interpret=bool(interpret)
    )
    return _uncenter(qsum_p[0, :k], qxc[:k], qxc2[:k], center)


def _affine_params(means, variances, weights):
    """The (A, B, c) of ``ll = x@A + x²@B + c``; ``means`` pre-centered.

    Single source of truth for both the Pallas and XLA paths (tests assert
    the two agree — keep them agreeing by construction).
    """
    d = means.shape[1]
    inv_var = 1.0 / variances
    A = (means * inv_var).T  # (d, k)
    B = (-0.5 * inv_var).T  # (d, k)
    c = (
        jnp.log(weights)
        - 0.5 * (d * jnp.log(2.0 * jnp.pi) + jnp.sum(jnp.log(variances), axis=1))
        - 0.5 * jnp.sum(means**2 * inv_var, axis=1)
    )  # (k,)
    return A, B, c


def _prep_params(means, variances, weights, d_tot, k_pad):
    """:func:`_affine_params` padded to (d_tot, k_pad) for the kernel.

    Rows for the weight/ones columns of x_aug and for padded feature dims
    are zero; padded centers get c = -1e30 so their posterior underflows.
    """
    k, d = means.shape
    A0, B0, c0 = _affine_params(means, variances, weights)
    A = jnp.zeros((d_tot, k_pad), jnp.float32).at[:d, :k].set(A0)
    B = jnp.zeros((d_tot, k_pad), jnp.float32).at[:d, :k].set(B0)
    c = jnp.full((1, k_pad), -1e30, jnp.float32).at[0, :k].set(c0)
    return A, B, c


def _uncenter(qsum, qxc, qxc2, center):
    """Moments of x from moments of ``x - center`` (exact shift identity)."""
    qx = qxc + qsum[:, None] * center[None]
    qx2 = qxc2 + 2.0 * center[None] * qxc + qsum[:, None] * center[None] ** 2
    return qsum, qx, qx2


def augment_rows(
    xc: jax.Array, row_weights: Optional[jax.Array] = None
) -> jax.Array:
    """Pad an (already centered) sample into the kernel's augmented layout.

    Features + weight column + ones column padded up to a lane multiple,
    rows to the tile height; the last two columns are the per-row weight
    (scales q in-kernel; 0 for padding rows) and a constant 1 (yields
    qsum). Build this ONCE outside any EM loop — it is loop-invariant.
    Rows are padded to the autotuned tile height (lookup-only; see
    :func:`_tile_n` — :func:`moments_from_aug` re-fits its grid tile to the
    padded row count, so a tile change between the two calls stays exact).
    """
    n, d = xc.shape
    d_tot = _round_up(d + 2, _LANE)
    tile = _tile_n()
    n_pad = _round_up(max(n, tile), tile)
    w = jnp.ones((n,), jnp.float32) if row_weights is None else row_weights
    x_aug = jnp.zeros((n_pad, d_tot), jnp.float32)
    x_aug = x_aug.at[:n, :d].set(xc)
    x_aug = x_aug.at[:n, d_tot - 2].set(w)
    x_aug = x_aug.at[:, d_tot - 1].set(1.0)
    return x_aug


def moments_from_aug(
    x_aug: jax.Array,
    d: int,
    means_c: jax.Array,
    variances: jax.Array,
    weights: jax.Array,
    *,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Kernel call on a pre-augmented sample; ``means_c`` centered the same
    way as ``x_aug``. Returns centered moments (caller applies
    :func:`_uncenter` if it needs raw-x moments)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = means_c.shape[0]
    d_tot = x_aug.shape[1]
    k_pad = _round_up(k, _LANE)
    A, B, c = _prep_params(
        jnp.asarray(means_c, jnp.float32),
        jnp.asarray(variances, jnp.float32),
        jnp.asarray(weights, jnp.float32),
        d_tot,
        k_pad,
    )
    tile_n = _fit_tile(x_aug.shape[0], _tile_n())
    qx_full, qx2_full = _moments_pallas(
        x_aug, A, B, c, tile_n=tile_n, interpret=bool(interpret)
    )
    qsum = qx_full[:k, d_tot - 1]  # the ones column of q^T x_aug
    return qsum, qx_full[:k, :d], qx2_full[:k, :d]


def gmm_moments(
    x: jax.Array,
    means: jax.Array,
    variances: jax.Array,
    weights: jax.Array,
    row_weights: Optional[jax.Array] = None,
    *,
    center: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused E-step + weighted moments: returns ``(qsum, qx, qx2)``.

    ``qsum[k] = Σ_n w_n q_nk``, ``qx = Σ_n w_n q_nk x_n``,
    ``qx2 = Σ_n w_n q_nk x_n²`` — the sufficient statistics for an EM M-step
    and the raw moments of a Fisher Vector — computed without materializing
    the (n, k) responsibilities.

    Local (per-shard) computation: under ``shard_map`` over a data axis the
    caller ``psum``s the three outputs, mirroring the reference's treeReduce
    of per-partition statistics.
    """
    x = jnp.asarray(x, jnp.float32)
    d = x.shape[1]
    if center is None:
        center = jnp.mean(x, axis=0)
    x_aug = augment_rows(x - center[None], row_weights)
    qsum, qxc, qxc2 = moments_from_aug(
        x_aug, d, means - center[None], variances, weights, interpret=interpret
    )
    return _uncenter(qsum, qxc, qxc2, center)


def gmm_moments_xla(
    x: jax.Array,
    means: jax.Array,
    variances: jax.Array,
    weights: jax.Array,
    row_weights: Optional[jax.Array] = None,
    center: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """XLA formulation of :func:`gmm_moments` (materializes the (n, k)
    responsibilities — fine when n·k fits HBM; works on any backend and
    under ``vmap``). Same centered affine log-density as the kernel, so the
    two paths agree to float rounding and neither ever builds an (n, k, d)
    broadcast."""
    x = jnp.asarray(x, jnp.float32)
    means = jnp.asarray(means, jnp.float32)
    variances = jnp.asarray(variances, jnp.float32)
    weights = jnp.asarray(weights, jnp.float32)
    if center is None:
        center = jnp.mean(x, axis=0)
    xc = x - center[None]
    A, B, c = _affine_params(means - center[None], variances, weights)
    ll = xc @ A + (xc * xc) @ B + c[None]
    q = jax.nn.softmax(ll, axis=1)
    if row_weights is not None:
        q = q * row_weights[:, None]
    qsum = jnp.sum(q, axis=0)
    return _uncenter(qsum, q.T @ xc, q.T @ (xc * xc), center)


_CHUNK_ROWS = 1 << 17  # 128k rows/chunk: q chunk is 128k×k — ≤128 MB at k=256


def gmm_moments_auto(
    x: jax.Array,
    means: jax.Array,
    variances: jax.Array,
    weights: jax.Array,
    row_weights: Optional[jax.Array] = None,
    center: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Default moments path.

    Small inputs go through one fused XLA program (compile-cheap, measured
    at parity). Large inputs on TPU go through the copy-free Pallas kernel
    (:func:`gmm_moments_sep`): measured at the kernel's design point
    (n=1e7, d=64, k=256 — the reference's 1e7-sample GMM regime,
    ``ImageNetSiftLcsFV.scala:197-218``) it beats the chunked-XLA scan
    1.2-1.3× on v5e (0.265 s vs 0.315 s single-sync; bench extra
    ``moments_design_point``) and allocates no (n, k) or padded-input
    intermediate. Off-TPU large inputs use the ``lax.scan`` of XLA row
    chunks (same accumulator shape, any backend). The round-2 augmented
    kernel (:func:`gmm_moments`) lost this comparison — its lane-padded
    input copy OOMs the design point outright — which is why the auto path
    previously preferred XLA.
    """
    n = x.shape[0]
    if n <= _CHUNK_ROWS:
        return gmm_moments_xla(x, means, variances, weights, row_weights, center)
    if jax.default_backend() == "tpu":
        return gmm_moments_sep(x, means, variances, weights, row_weights,
                               center=center)

    x = jnp.asarray(x, jnp.float32)
    k, d = means.shape
    if center is None:
        center = jnp.mean(x, axis=0)
    # Full chunks are read in place via dynamic_slice (no padded copy of x —
    # transient memory stays O(chunk·(d+k))); the ragged tail is one extra
    # small call.
    num_full = n // _CHUNK_ROWS
    w = row_weights

    def step(acc, i):
        start = i * _CHUNK_ROWS
        xi = jax.lax.dynamic_slice_in_dim(x, start, _CHUNK_ROWS, 0)
        wi = None if w is None else jax.lax.dynamic_slice_in_dim(w, start, _CHUNK_ROWS, 0)
        qsum, qx, qx2 = gmm_moments_xla(xi, means, variances, weights, wi, center)
        return (acc[0] + qsum, acc[1] + qx, acc[2] + qx2), None

    init = (
        jnp.zeros((k,), jnp.float32),
        jnp.zeros((k, d), jnp.float32),
        jnp.zeros((k, d), jnp.float32),
    )
    acc, _ = jax.lax.scan(step, init, jnp.arange(num_full))
    tail = n - num_full * _CHUNK_ROWS
    if tail:
        qsum, qx, qx2 = gmm_moments_xla(
            x[num_full * _CHUNK_ROWS :],
            means,
            variances,
            weights,
            None if w is None else w[num_full * _CHUNK_ROWS :],
            center,
        )
        acc = (acc[0] + qsum, acc[1] + qx, acc[2] + qx2)
    return acc
