"""Pallas TPU kernels for the framework's hot ops.

Each kernel has an XLA fallback selected automatically off-TPU (and usable
under ``vmap``); the Pallas paths are the HBM-bandwidth-bound inner loops
where XLA's fusion leaves traffic on the table (SURVEY.md §2.8 TPU mapping).
Every kernel is generated over a small variant space (loop order, fusion
span — ``ops/pallas/variants.py``) and the autotuner arbitrates the
measured winner per ``(device, shape bucket, precision tier, variant)``.
"""

from keystone_tpu.ops.pallas import autotune, variants
from keystone_tpu.ops.pallas.extraction import (
    conv_norm,
    conv_norm_plan,
    conv_norm_pool,
    conv_pool_plan,
    default_interpret,
    fv_encode_plan,
    fv_moments,
    pallas_enabled,
    pool_sum,
    pool_sum_plan,
    sift_bins_plan,
    sift_oriented_bins,
)
from keystone_tpu.ops.pallas.moments import (
    gmm_moments,
    gmm_moments_auto,
    gmm_moments_sep,
    gmm_moments_xla,
)

__all__ = [
    "autotune",
    "conv_norm",
    "conv_norm_plan",
    "conv_norm_pool",
    "conv_pool_plan",
    "default_interpret",
    "fv_encode_plan",
    "fv_moments",
    "gmm_moments",
    "gmm_moments_auto",
    "gmm_moments_sep",
    "gmm_moments_xla",
    "pallas_enabled",
    "pool_sum",
    "pool_sum_plan",
    "sift_bins_plan",
    "sift_oriented_bins",
    "variants",
]
