"""Pallas TPU kernels for the framework's hot ops.

Each kernel has an XLA fallback selected automatically off-TPU (and usable
under ``vmap``); the Pallas paths are the HBM-bandwidth-bound inner loops
where XLA's fusion leaves traffic on the table (SURVEY.md §2.8 TPU mapping).
"""

from keystone_tpu.ops.pallas import autotune
from keystone_tpu.ops.pallas.extraction import (
    conv_norm,
    default_interpret,
    fv_moments,
    pallas_enabled,
    pool_sum,
    sift_oriented_bins,
)
from keystone_tpu.ops.pallas.moments import (
    gmm_moments,
    gmm_moments_auto,
    gmm_moments_sep,
    gmm_moments_xla,
)

__all__ = [
    "autotune",
    "conv_norm",
    "default_interpret",
    "fv_moments",
    "gmm_moments",
    "gmm_moments_auto",
    "gmm_moments_sep",
    "gmm_moments_xla",
    "pallas_enabled",
    "pool_sum",
    "sift_oriented_bins",
]
