"""Fused Pallas kernels for the per-item extraction hot paths.

KeystoneML ran SIFT, Fisher-vector encoding, convolution and pooling in its
native C++/JNI layer (PAPER.md layer map) because generic execution was too
slow; our port composes XLA ops, which is correct but leaves HBM traffic on
the table in exactly the same places. This module is the kernel family that
closes that gap, following the ``ops/pallas/moments.py`` pattern: VMEM
BlockSpecs, padded tiles with mask poison, ``interpret=`` fallback so the
same kernels run (and are parity-tested) on CPU, and jit-static gating so
``KEYSTONE_PALLAS=0`` restores the exact prior XLA program.

Kernels and their XLA twins (the twin is always the pre-existing path):

====================  =============================================  ========
kernel                fuses                                          default
====================  =============================================  ========
``sift.bins``         orientation binning × column-selection matmul  auto
                      (kills the (..., 8, H, W) energy tensor)
``fv.encode``         posterior softmax × moment accumulation per    auto
                      image (kills the (n, n_desc, k) posteriors)
``conv.norm``         im2col matmul + per-patch mean/sd              explicit
                      normalization + whitener shift (kills raw/
                      s1/s2 intermediates)
``pool.sum``          pixel-function + separable sum-pool selection  explicit
                      matmuls (max pooling stays on the XLA twin)
====================  =============================================  ========

"auto" kernels engage on TPU under the default ``KEYSTONE_PALLAS=auto``;
"explicit" kernels (rank-3 in-VMEM contractions the moments kernel never
exercised on real silicon) engage only under ``KEYSTONE_PALLAS=1`` until a
pod run validates their lowering — the same measured-promotion discipline
``gmm_moments_auto`` applied. Tile heights come from the device-keyed
autotuner (``ops/pallas/autotune.py``); every tile argument is jit-static.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.ops.pallas import autotune
from keystone_tpu.utils import knobs

_LANE = 128
NUM_BIN_T = 8  # SIFT orientation bins (mirrors ops/images/sift.py)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def pallas_enabled(auto_ok: bool = True) -> bool:
    """Knob-resolved kernel/twin selection (``KEYSTONE_PALLAS``).

    ``"1"`` forces every kernel on (interpret mode off-TPU — the parity-test
    configuration); ``"0"`` forces every kernel off (the HLO-level-no-op
    contract: twins are the untouched prior code paths); ``"auto"`` (the
    default) engages only the auto-grade kernels (``auto_ok=True``) and only
    on TPU. Read this EAGERLY and thread the decision through jit as a
    static argument — an env read inside a traced body bakes stale state
    (the PR-6 tiers lesson)."""
    v = knobs.get("KEYSTONE_PALLAS")
    if v == "1":
        return True
    if v == "0":
        return False
    return auto_ok and jax.default_backend() == "tpu"


def default_interpret() -> bool:
    """Pallas interpret mode everywhere but real TPU (the moments-kernel
    convention): the same kernel code path is exercised by the CPU test
    mesh."""
    return jax.default_backend() != "tpu"


def _count(event: str, **labels) -> None:
    """``pallas.engaged{kernel}`` / ``pallas.fallback{kernel,reason}`` —
    the overlap-layer convention: tests and the bench can see which
    kernels actually ran without scraping logs. Entry wrappers count once
    per trace (they run at trace time under jit), so the counters report
    engagement decisions, not per-dispatch volume."""
    from keystone_tpu.telemetry import get_registry

    get_registry().inc(f"pallas.{event}", **labels)


# ---------------------------------------------------------------------------
# SIFT: fused orientation binning × column-selection matmul
# ---------------------------------------------------------------------------
#
# The XLA matmul path materializes the orientation-energy tensor
# (..., 8, H, W) in HBM — an 8x blowup of the (smoothed) image — before the
# first selection matmul consumes it. The kernel streams (mag, angle) row
# tiles HBM→VMEM once, expands the 8 orientation maps in VMEM, and
# immediately contracts each against the column-selection matrix, so only
# the (..., 8, H, nx*4)-shaped result (typically ~Q/W the size) ever leaves
# the chip.


def _sift_bins_kernel(mag_ref, ang_ref, sel_ref, out_ref, *, q_pad: int):
    # bf16-input variant (KEYSTONE_PRECISION_TIER=bf16): the refs stream
    # bfloat16 tiles HBM→VMEM (half the traffic of the kernel's dominant
    # read) and upcast IN VMEM — all binning arithmetic and the selection
    # matmul accumulate f32. For f32 inputs the astype is a no-op, so the
    # f32-tier program is byte-identical to the pre-tier kernel.
    mag = mag_ref[:].astype(jnp.float32)  # (TR, W)
    ang = ang_ref[:].astype(jnp.float32)
    ft = jnp.mod(ang * (NUM_BIN_T / (2.0 * jnp.pi)), NUM_BIN_T)
    sel = sel_ref[:]  # (W, Qp); padded columns are zero -> poison-free
    for t in range(NUM_BIN_T):
        d = jnp.mod(ft - float(t), NUM_BIN_T)
        w = jnp.maximum(0.0, 1.0 - d) + jnp.maximum(
            0.0, d - (NUM_BIN_T - 1.0)
        )
        out_ref[:, t * q_pad : (t + 1) * q_pad] = jnp.dot(
            mag * w, sel, preferred_element_type=jnp.float32
        )


@functools.partial(jax.jit, static_argnames=("tile_r", "interpret"))
def _sift_bins_pallas(mag2, ang2, sel_p, *, tile_r: int, interpret: bool):
    rows, w = mag2.shape
    q_pad = sel_p.shape[1]
    grid = (pl.cdiv(rows, tile_r),)
    rows_pad = _round_up(rows, tile_r)
    # Ragged final tile: input reads past ``rows`` return garbage lanes
    # (the proven moments-sep pattern) whose computation is row-local and
    # lands in output rows >= ``rows`` — trimmed by the caller. The padded
    # ``sel`` columns are zero, so lane padding in Q is poison-free too.
    return pl.pallas_call(
        functools.partial(_sift_bins_kernel, q_pad=q_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_r, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((w, q_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile_r, NUM_BIN_T * q_pad), lambda i: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (rows_pad, NUM_BIN_T * q_pad), jnp.float32
        ),
        interpret=interpret,
    )(mag2, ang2, sel_p)


def sift_bins_tile(rows: int, width: int, q: int,
                   allow_sweep: bool = True, tier: str = "f32") -> int:
    """Autotuned row-tile height for ``sift.bins`` at this shape bucket —
    and this precision tier: the tier joins the bucket key
    (``autotune.precision_bucket``), so a bf16-swept winner never serves an
    f32 call or vice versa, and the sweep itself times operands of the
    tier's storage dtype. ``allow_sweep=False`` is lookup-only — pass it
    when resolving from inside a trace (a sweep times real executions)."""
    bucket = autotune.precision_bucket(
        autotune.shape_bucket(rows, width), tier
    )
    q_pad = _round_up(max(q, 1), _LANE)
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def build(tile):
        key = jax.random.key(0)
        mag = jax.random.uniform(key, (rows, width), jnp.float32)
        ang = jax.random.uniform(
            key, (rows, width), jnp.float32, -jnp.pi, jnp.pi
        )
        sel = jnp.zeros((width, q_pad), jnp.float32).at[:, :q].set(1.0)
        interp = default_interpret()
        return lambda i: _sift_bins_pallas(
            (mag + float(i)).astype(in_dtype), ang.astype(in_dtype), sel,
            tile_r=tile, interpret=interp,
        )

    candidates = [t for t in (128, 256, 512, 1024) if t <= max(rows, 128)]
    return autotune.resolve(
        "sift.bins", bucket, candidates or [128], 256,
        measure=autotune.chained_measure(build) if allow_sweep else None,
    )


def sift_oriented_bins(mag, angle, sel: np.ndarray, *, tile_r: int = 256,
                       interpret: Optional[bool] = None, tier: str = "f32"):
    """Fused ``energies @ sel`` without materializing the energies:
    (..., H, W) magnitude/orientation + (W, Q) 0/1 selection matrix ->
    (..., NUM_BIN_T, H, Q). Traceable (called inside the SIFT extractor's
    jit); ``tile_r`` must already be resolved (jit-static). ``tier="bf16"``
    (caller-resolved, like the tile) stores the streamed mag/angle tiles in
    bfloat16 — the kernel upcasts in VMEM and accumulates f32; output is
    always f32."""
    lead = mag.shape[:-2]
    h, w = mag.shape[-2], mag.shape[-1]
    q = sel.shape[1]
    q_pad = _round_up(max(q, 1), _LANE)
    sel_p = jnp.zeros((w, q_pad), jnp.float32).at[:, :q].set(
        jnp.asarray(sel, jnp.float32)
    )
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32
    rows = int(np.prod(lead, dtype=np.int64)) * h if lead else h
    mag2 = mag.reshape(rows, w).astype(in_dtype)
    ang2 = angle.reshape(rows, w).astype(in_dtype)
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="sift.bins")
    out = _sift_bins_pallas(
        mag2, ang2, sel_p, tile_r=int(tile_r), interpret=bool(interpret)
    )
    out = out[:rows].reshape(*lead, h, NUM_BIN_T, q_pad)[..., :q]
    return jnp.moveaxis(out, -2, -3)  # (..., T, H, Q)


# ---------------------------------------------------------------------------
# Fisher vector: fused posterior softmax × per-image moment accumulation
# ---------------------------------------------------------------------------
#
# The XLA batch encoder materializes the (n_img, n_desc, k) posterior tensor
# between the log-density gemm and the moment einsums. Per grid step this
# kernel holds one (tile_nd, d) descriptor tile in VMEM, computes its
# posterior rows, and folds them straight into the per-image (k, d)
# accumulators — posteriors never reach HBM. Gradient formulas (the actual
# Fisher encode) are a cheap XLA epilogue over the (n_img, k, d) moments.


def _fv_moments_kernel(
    x_ref, a_ref, b_ref, c_ref, qsum_ref, qx_ref, qx2_ref, *, n_desc: int
):
    j = pl.program_id(1)  # descriptor tile (fastest grid axis)

    @pl.when(j == 0)
    def _():
        qsum_ref[:] = jnp.zeros_like(qsum_ref)
        qx_ref[:] = jnp.zeros_like(qx_ref)
        qx2_ref[:] = jnp.zeros_like(qx2_ref)

    # bf16-input variant: descriptor tiles stream HBM→VMEM in bfloat16
    # under the tier and upcast here — posterior/moment arithmetic always
    # accumulates f32 (no-op astype for f32 inputs: byte-identical)
    x = x_ref[0].astype(jnp.float32)  # (TND, d)
    tile_nd = x.shape[0]
    row_ids = j * tile_nd + jax.lax.broadcasted_iota(
        jnp.int32, (tile_nd, 1), 0
    )
    valid = row_ids < n_desc  # False in the ragged final tile
    x = jnp.where(valid, x, 0.0)  # poison OOB garbage before it hits x**2
    x2 = x * x
    ll = (
        jnp.dot(x, a_ref[:], preferred_element_type=jnp.float32)
        + jnp.dot(x2, b_ref[:], preferred_element_type=jnp.float32)
        + c_ref[:]
    )  # (TND, Kp); padded centers carry c = -1e30 -> softmax ~ 0
    m = jnp.max(ll, axis=1, keepdims=True)
    e = jnp.exp(ll - m)
    q = e / jnp.sum(e, axis=1, keepdims=True)
    q = jnp.where(valid, q, 0.0)  # padded descriptor rows contribute nothing

    qsum_ref[:] += jnp.sum(q, axis=0, keepdims=True)
    qt = q.T  # (Kp, TND)
    qx_ref[0] += jnp.dot(qt, x, preferred_element_type=jnp.float32)
    qx2_ref[0] += jnp.dot(qt, x2, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("tile_nd", "interpret"))
def _fv_moments_pallas(x, A, B, c, *, tile_nd: int, interpret: bool):
    n_img, nd, d = x.shape
    k_pad = A.shape[1]
    grid = (n_img, pl.cdiv(nd, tile_nd))
    return pl.pallas_call(
        functools.partial(_fv_moments_kernel, n_desc=nd),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, tile_nd, d), lambda i, j: (i, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((d, k_pad), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k_pad), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, k_pad), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, k_pad, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, k_pad, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_img, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_img, k_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((n_img, k_pad, d), jnp.float32),
        ],
        interpret=interpret,
    )(x, A, B, c)


def fv_encode_tile(nd: int, d: int, k: int,
                   allow_sweep: bool = True, tier: str = "f32") -> int:
    """Autotuned descriptor-tile height for ``fv.encode``; the precision
    tier joins the shape bucket (``autotune.precision_bucket``) and the
    sweep times operands of the tier's storage dtype.
    ``allow_sweep=False`` is lookup-only (resolution from inside a
    trace)."""
    bucket = autotune.precision_bucket(autotune.shape_bucket(nd, d, k), tier)
    k_pad = _round_up(max(k, 1), _LANE)
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def build(tile):
        key = jax.random.key(1)
        x = jax.random.normal(key, (2, nd, d), jnp.float32)
        A = jax.random.normal(key, (d, k_pad), jnp.float32) * 0.1
        B = -jnp.abs(jax.random.normal(key, (d, k_pad), jnp.float32)) * 0.1
        c = jnp.zeros((1, k_pad), jnp.float32)
        interp = default_interpret()
        return lambda i: _fv_moments_pallas(
            (x + float(i) * 1e-3).astype(in_dtype), A, B, c,
            tile_nd=tile, interpret=interp,
        )

    candidates = [t for t in (64, 128, 256, 512) if t <= _round_up(nd, 64)]
    return autotune.resolve(
        "fv.encode", bucket, candidates or [64], 256,
        measure=autotune.chained_measure(build) if allow_sweep else None,
    )


def fv_moments(x, means, variances, weights, *, tile_nd: int = 256,
               interpret: Optional[bool] = None, tier: str = "f32"):
    """Per-image uncentered GMM moments without HBM posteriors:
    (n_img, nd, d) descriptors -> ``(qsum (n,k), qx (n,k,d), qx2 (n,k,d))``.
    Traceable; the caller resolves ``tile_nd`` eagerly (jit-static). Same
    affine log-density as every other moments path (``_affine_params`` —
    the single source of truth the parity tests pin). ``tier="bf16"``
    streams the descriptor tiles in bfloat16 (the kernel's dominant read);
    GMM parameters, posterior math and the moment accumulators stay f32."""
    from keystone_tpu.ops.pallas.moments import _prep_params

    x = jnp.asarray(x, jnp.float32)
    if tier == "bf16":
        x = x.astype(jnp.bfloat16)
    d = x.shape[2]
    k = means.shape[0]
    k_pad = _round_up(k, _LANE)
    A, B, c = _prep_params(
        jnp.asarray(means, jnp.float32),
        jnp.asarray(variances, jnp.float32),
        jnp.asarray(weights, jnp.float32),
        d, k_pad,
    )
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="fv.encode")
    qsum, qx, qx2 = _fv_moments_pallas(
        x, A, B, c, tile_nd=int(tile_nd), interpret=bool(interpret)
    )
    return qsum[:, :k], qx[:, :k], qx2[:, :k]


# ---------------------------------------------------------------------------
# Convolver: fused im2col matmul + per-patch normalization
# ---------------------------------------------------------------------------
#
# The XLA twin runs three convolutions (raw, patch-sum, patch-sum-of-
# squares) over the batch and fuses the normalization arithmetic; each conv
# re-reads the image from HBM and the raw result round-trips before the
# epilogue. The kernel holds ONE image in VMEM per grid step, accumulates
# the k² shifted matmuls and the patch statistics in-register, applies the
# normalization and whitener shift, and writes only the finished output
# tile. Filter columns are tiled (``tile_f``) so the accumulator fits VMEM.


def _conv_norm_kernel(
    x_ref, f_ref, fsum_ref, mf_ref, out_ref,
    *, ksz: int, chans: int, res_h: int, res_w: int,
    normalize: bool, var_constant: float,
):
    x = x_ref[0]  # (H, W, C)
    tile_f = f_ref.shape[3]
    p = res_h * res_w
    acc = jnp.zeros((p, tile_f), jnp.float32)
    s1 = jnp.zeros((p, 1), jnp.float32)
    s2 = jnp.zeros((p, 1), jnp.float32)
    for dy in range(ksz):
        for dx in range(ksz):
            xs = x[dy : dy + res_h, dx : dx + res_w, :].reshape(p, chans)
            acc += jnp.dot(
                xs, f_ref[dy, dx], preferred_element_type=jnp.float32
            )
            if normalize:
                s1 += jnp.sum(xs, axis=1, keepdims=True)
                s2 += jnp.sum(xs * xs, axis=1, keepdims=True)
    out = acc
    if normalize:
        n = float(ksz * ksz * chans)
        mean = s1 / n
        var = (s2 - s1 * mean) / (n - 1.0)
        sd = jnp.sqrt(var + var_constant)
        out = (acc - mean * fsum_ref[:]) / sd
    out_ref[0] = out - mf_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "ksz", "chans", "res_h", "res_w", "normalize", "var_constant",
        "tile_f", "interpret",
    ),
)
def _conv_norm_pallas(
    imgs, filt, fsum, mf, *, ksz: int, chans: int, res_h: int, res_w: int,
    normalize: bool, var_constant: float, tile_f: int, interpret: bool,
):
    n, h, w, _ = imgs.shape
    nf_pad = filt.shape[3]
    grid = (n, nf_pad // tile_f)
    p = res_h * res_w
    out = pl.pallas_call(
        functools.partial(
            _conv_norm_kernel, ksz=ksz, chans=chans, res_h=res_h,
            res_w=res_w, normalize=normalize, var_constant=var_constant,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, h, w, chans), lambda i, f: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ksz, ksz, chans, tile_f), lambda i, f: (0, 0, 0, f),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, tile_f), lambda i, f: (0, f), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_f), lambda i, f: (0, f), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, p, tile_f), lambda i, f: (i, 0, f), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, p, nf_pad), jnp.float32),
        interpret=interpret,
    )(imgs, filt, fsum, mf)
    return out


_CONV_VMEM_BUDGET = 12 << 20  # conservative f32 working-set bound per step


def conv_norm_tile(h: int, w: int, chans: int, ksz: int, nf: int,
                   allow_sweep: bool = True):
    """Autotuned filter-tile width for ``conv.norm``, constrained to tiles
    whose per-step working set fits the VMEM budget. Returns None when no
    candidate fits (caller falls back to the XLA twin).
    ``allow_sweep=False`` is lookup-only."""
    res_h, res_w = h - ksz + 1, w - ksz + 1
    p = res_h * res_w

    def fits(tf: int) -> bool:
        est = 4 * (
            h * w * chans            # resident image
            + ksz * ksz * chans * tf  # filter tile
            + 3 * p * tf              # acc + epilogue temporaries
            + 2 * p                   # s1 / s2
        )
        return est < _CONV_VMEM_BUDGET

    candidates = [t for t in (64, 128, 256, 512) if fits(t)]
    if not candidates:
        _count("fallback", kernel="conv.norm", reason="vmem")
        return None
    bucket = autotune.shape_bucket(h, w, nf)

    def build(tile):
        key = jax.random.key(2)
        xi = jax.random.uniform(key, (2, h, w, chans), jnp.float32)
        nf_pad = _round_up(nf, tile)
        fi = jax.random.normal(key, (ksz, ksz, chans, nf_pad), jnp.float32)
        fs = jnp.sum(fi.reshape(-1, nf_pad), axis=0, keepdims=True)
        mfz = jnp.zeros((1, nf_pad), jnp.float32)
        args = dict(
            ksz=ksz, chans=chans, res_h=res_h, res_w=res_w, normalize=True,
            var_constant=10.0, tile_f=tile, interpret=default_interpret(),
        )
        return lambda i: _conv_norm_pallas(
            xi + float(i) * 1e-3, fi, fs, mfz, **args
        )

    return autotune.resolve(
        "conv.norm", bucket, candidates, candidates[0],
        measure=autotune.chained_measure(build) if allow_sweep else None,
    )


def conv_norm(imgs, filters, *, num_channels: int, normalize: bool,
              var_constant: float, whitener_means=None, tile_f: int = 128,
              interpret: Optional[bool] = None):
    """Fused Convolver forward: (N, H, W, C) images + (nF, k·k·C) filters
    (reference patch layout) -> (N, resH, resW, nF). Traceable; ``tile_f``
    pre-resolved via :func:`conv_norm_tile`."""
    imgs = jnp.asarray(imgs, jnp.float32)
    n, h, w, c = imgs.shape
    nf = filters.shape[0]
    k2 = filters.shape[1] // num_channels
    ksz = int(round(k2**0.5))
    res_h, res_w = h - ksz + 1, w - ksz + 1
    tile_f = int(tile_f)
    nf_pad = _round_up(nf, tile_f)
    filt = jnp.zeros((nf_pad, ksz * ksz * c), jnp.float32).at[:nf].set(
        jnp.asarray(filters, jnp.float32)
    )
    # padded filters are all-zero -> their output columns are exactly
    # -mf_pad = 0 after the normalization arithmetic; trimmed below anyway
    filt = filt.reshape(nf_pad, ksz, ksz, c).transpose(1, 2, 3, 0)
    fsum = jnp.sum(filt.reshape(-1, nf_pad), axis=0, keepdims=True)
    mf = jnp.zeros((1, nf_pad), jnp.float32)
    if whitener_means is not None:
        mf = mf.at[:, :nf].set(
            (jnp.asarray(whitener_means, jnp.float32) @ filters.T)[None]
        )
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="conv.norm")
    out = _conv_norm_pallas(
        imgs, filt, fsum, mf, ksz=ksz, chans=c, res_h=res_h, res_w=res_w,
        normalize=bool(normalize), var_constant=float(var_constant),
        tile_f=tile_f, interpret=bool(interpret),
    )
    return out.reshape(n, res_h, res_w, nf_pad)[..., :nf]


# ---------------------------------------------------------------------------
# Pooler: fused pixel-function + separable sum-pool selection matmuls
# ---------------------------------------------------------------------------
#
# Sum pooling over clamped windows is separable into two 0/1 selection
# matmuls (the ``_bin_select_matrix`` trick): out = Myᵀ · f(img) · Mx per
# channel. The kernel applies the elementwise pixel function and both
# contractions in VMEM, so the f(img) intermediate never reaches HBM.
# Max pooling is not a matmul; it stays on the XLA reduce_window twin.


def pool_select_matrix(dim: int, stride: int, pool_size: int) -> np.ndarray:
    """(dim, num_pools) 0/1 matrix: column p sums pixels
    [p·stride, p·stride + pool_size) ∩ [0, dim) — the clamped windows of
    ``Pooler`` (``_pool_geometry``), exactly (clamping = missing rows)."""
    stride_start = pool_size // 2
    num_pools = -(-(dim - stride_start) // stride)
    m = np.zeros((dim, num_pools), np.float32)
    for pi in range(num_pools):
        lo = pi * stride
        hi = min(lo + pool_size, dim)
        m[lo:hi, pi] = 1.0
    return m


def _pool_sum_kernel(x_ref, my_ref, mx_ref, out_ref, *, pixel_fn):
    y = x_ref[0]  # (H, W, TC)
    if pixel_fn is not None:
        y = pixel_fn(y)
    h, w, tc = y.shape
    p = my_ref.shape[1]
    q = mx_ref.shape[1]
    # contract H: (P, H) @ (H, W·TC) — one clean 2D matmul
    t1 = jnp.dot(
        my_ref[:].T, y.reshape(h, w * tc), preferred_element_type=jnp.float32
    ).reshape(p, w, tc)
    # contract W: regroup channels-major so the second contraction is 2D too
    t2 = jnp.dot(
        jnp.transpose(t1, (0, 2, 1)).reshape(p * tc, w),
        mx_ref[:],
        preferred_element_type=jnp.float32,
    ).reshape(p, tc, q)
    out_ref[0] = jnp.transpose(t2, (0, 2, 1))  # (P, Q, TC)


@functools.partial(
    jax.jit, static_argnames=("pixel_fn", "tile_c", "interpret")
)
def _pool_sum_pallas(imgs, my, mx, *, pixel_fn, tile_c: int, interpret: bool):
    n, h, w, c_pad = imgs.shape
    p, q = my.shape[1], mx.shape[1]
    grid = (n, c_pad // tile_c)
    return pl.pallas_call(
        functools.partial(_pool_sum_kernel, pixel_fn=pixel_fn),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, h, w, tile_c), lambda i, cc: (i, 0, 0, cc),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((h, p), lambda i, cc: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((w, q), lambda i, cc: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, p, q, tile_c), lambda i, cc: (i, 0, 0, cc),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, p, q, c_pad), jnp.float32),
        interpret=interpret,
    )(imgs, my, mx)


_POOL_VMEM_BUDGET = 8 << 20  # f32 bound on the per-step input block


def pool_block_fits(h: int, w: int, c: int) -> bool:
    """Whether one (H, W, c) f32 block fits the pool kernel's VMEM budget
    — the eligibility bound for the untiled (pixel-function) form."""
    return 4 * h * w * c < _POOL_VMEM_BUDGET


def pool_sum_tile(h: int, w: int, c: int):
    """Autotuned channel-tile width for ``pool.sum``, or None when no
    candidate fits the VMEM budget (caller falls back to the XLA twin —
    the same contract as :func:`conv_norm_tile`). EAGER-only."""
    candidates = [
        t for t in (64, 128, 256, 512) if pool_block_fits(h, w, t)
    ]
    if not candidates:
        _count("fallback", kernel="pool.sum", reason="vmem")
        return None
    return autotune.resolve(
        "pool.sum", autotune.shape_bucket(h, w, c), candidates,
        candidates[0], measure=None,
    )


def pool_sum(imgs, stride: int, pool_size: int,
             pixel_fn: Optional[Callable] = None, *, tile_c: int = 128,
             interpret: Optional[bool] = None):
    """Fused sum-Pooler forward over a batch: (N, H, W, C) -> (N, P, Q, C).
    ``pixel_fn`` must be shape/dtype-preserving (checked by the caller via
    ``eval_shape``); when one is present the kernel never tiles or pads
    the channel axis — each grid step hands the function the FULL
    (H, W, C) block, so even a channel-mixing function stays correct."""
    imgs = jnp.asarray(imgs, jnp.float32)
    n, h, w, c = imgs.shape
    if pixel_fn is not None:
        tile_c = c_pad = c
    else:
        tile_c = int(min(tile_c, _round_up(c, 8)))
        c_pad = _round_up(c, tile_c)
    if c_pad != c:
        imgs = jnp.pad(imgs, ((0, 0), (0, 0), (0, 0), (0, c_pad - c)))
    my = jnp.asarray(pool_select_matrix(h, stride, pool_size))
    mx = jnp.asarray(pool_select_matrix(w, stride, pool_size))
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="pool.sum")
    out = _pool_sum_pallas(
        imgs, my, mx, pixel_fn=pixel_fn, tile_c=tile_c,
        interpret=bool(interpret),
    )
    return out[..., :c]
