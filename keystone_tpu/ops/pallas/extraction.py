"""Fused Pallas kernels for the per-item extraction hot paths.

KeystoneML ran SIFT, Fisher-vector encoding, convolution and pooling in its
native C++/JNI layer (PAPER.md layer map) because generic execution was too
slow; our port composes XLA ops, which is correct but leaves HBM traffic on
the table in exactly the same places. This module is the kernel family that
closes that gap, following the ``ops/pallas/moments.py`` pattern: VMEM
BlockSpecs, padded tiles with mask poison, ``interpret=`` fallback so the
same kernels run (and are parity-tested) on CPU, and jit-static gating so
``KEYSTONE_PALLAS=0`` restores the exact prior XLA program.

Kernels and their XLA twins (the twin is always the pre-existing path):

====================  =============================================  ========
kernel                fuses                                          default
====================  =============================================  ========
``sift.bins``         orientation binning × column-selection matmul  auto
                      (kills the (..., 8, H, W) energy tensor)
``fv.encode``         posterior softmax × moment accumulation per    auto
                      image (kills the (n, n_desc, k) posteriors)
``conv.norm``         im2col matmul + per-patch mean/sd              explicit
                      normalization + whitener shift (kills raw/
                      s1/s2 intermediates)
``pool.sum``          pixel-function + separable sum-pool selection  explicit
                      matmuls (max pooling stays on the XLA twin)
====================  =============================================  ========

"auto" kernels engage on TPU under the default ``KEYSTONE_PALLAS=auto``;
"explicit" kernels (rank-3 in-VMEM contractions the moments kernel never
exercised on real silicon) engage only under ``KEYSTONE_PALLAS=1`` until a
pod run validates their lowering — the same measured-promotion discipline
``gmm_moments_auto`` applied. Tile heights come from the device-keyed
autotuner (``ops/pallas/autotune.py``); every tile argument is jit-static.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.ops.pallas import autotune
from keystone_tpu.utils import knobs

_LANE = 128
NUM_BIN_T = 8  # SIFT orientation bins (mirrors ops/images/sift.py)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def pallas_enabled(auto_ok: bool = True) -> bool:
    """Knob-resolved kernel/twin selection (``KEYSTONE_PALLAS``).

    ``"1"`` forces every kernel on (interpret mode off-TPU — the parity-test
    configuration); ``"0"`` forces every kernel off (the HLO-level-no-op
    contract: twins are the untouched prior code paths); ``"auto"`` (the
    default) engages only the auto-grade kernels (``auto_ok=True``) and only
    on TPU. Read this EAGERLY and thread the decision through jit as a
    static argument — an env read inside a traced body bakes stale state
    (the PR-6 tiers lesson)."""
    v = knobs.get("KEYSTONE_PALLAS")
    if v == "1":
        return True
    if v == "0":
        return False
    return auto_ok and jax.default_backend() == "tpu"


def default_interpret() -> bool:
    """Pallas interpret mode everywhere but real TPU (the moments-kernel
    convention): the same kernel code path is exercised by the CPU test
    mesh."""
    return jax.default_backend() != "tpu"


def _count(event: str, **labels) -> None:
    """``pallas.engaged{kernel}`` / ``pallas.fallback{kernel,reason}`` —
    the overlap-layer convention: tests and the bench can see which
    kernels actually ran without scraping logs. Entry wrappers count once
    per trace (they run at trace time under jit), so the counters report
    engagement decisions, not per-dispatch volume."""
    from keystone_tpu.telemetry import get_registry

    get_registry().inc(f"pallas.{event}", **labels)


# ---------------------------------------------------------------------------
# SIFT: fused orientation binning × column-selection matmul
# ---------------------------------------------------------------------------
#
# The XLA matmul path materializes the orientation-energy tensor
# (..., 8, H, W) in HBM — an 8x blowup of the (smoothed) image — before the
# first selection matmul consumes it. The kernel streams (mag, angle) row
# tiles HBM→VMEM once, expands the 8 orientation maps in VMEM, and
# immediately contracts each against the column-selection matrix, so only
# the (..., 8, H, nx*4)-shaped result (typically ~Q/W the size) ever leaves
# the chip.


def _sift_bins_kernel(mag_ref, ang_ref, sel_ref, out_ref, *, q_pad: int,
                      variant: str = "unroll"):
    # bf16-input variant (KEYSTONE_PRECISION_TIER=bf16): the refs stream
    # bfloat16 tiles HBM→VMEM (half the traffic of the kernel's dominant
    # read) and upcast IN VMEM — all binning arithmetic and the selection
    # matmul accumulate f32. For f32 inputs the astype is a no-op, so the
    # f32-tier program is byte-identical to the pre-tier kernel.
    mag = mag_ref[:].astype(jnp.float32)  # (TR, W)
    ang = ang_ref[:].astype(jnp.float32)
    ft = jnp.mod(ang * (NUM_BIN_T / (2.0 * jnp.pi)), NUM_BIN_T)
    sel = sel_ref[:]  # (W, Qp); padded columns are zero -> poison-free
    if variant == "stack":
        # generated loop-order variant: build all 8 weighted magnitude
        # maps at once and contract them in ONE (8·TR, W) @ (W, Qp)
        # matmul — 8x taller MXU pass instead of 8 short ones; per-slab
        # results are identical sums, just batched
        tr, wdim = mag.shape
        ts = jax.lax.broadcasted_iota(jnp.float32, (NUM_BIN_T, 1, 1), 0)
        d = jnp.mod(ft[None, :, :] - ts, float(NUM_BIN_T))
        w = jnp.maximum(0.0, 1.0 - d) + jnp.maximum(
            0.0, d - (NUM_BIN_T - 1.0)
        )
        res = jnp.dot(
            (mag[None, :, :] * w).reshape(NUM_BIN_T * tr, wdim), sel,
            preferred_element_type=jnp.float32,
        ).reshape(NUM_BIN_T, tr, q_pad)
        out_ref[:] = jnp.moveaxis(res, 0, 1).reshape(
            tr, NUM_BIN_T * q_pad
        )
        return
    for t in range(NUM_BIN_T):
        d = jnp.mod(ft - float(t), NUM_BIN_T)
        w = jnp.maximum(0.0, 1.0 - d) + jnp.maximum(
            0.0, d - (NUM_BIN_T - 1.0)
        )
        out_ref[:, t * q_pad : (t + 1) * q_pad] = jnp.dot(
            mag * w, sel, preferred_element_type=jnp.float32
        )


@functools.partial(
    jax.jit, static_argnames=("tile_r", "interpret", "variant")
)
def _sift_bins_pallas(mag2, ang2, sel_p, *, tile_r: int, interpret: bool,
                      variant: str = "unroll"):
    rows, w = mag2.shape
    q_pad = sel_p.shape[1]
    grid = (pl.cdiv(rows, tile_r),)
    rows_pad = _round_up(rows, tile_r)
    # Ragged final tile: input reads past ``rows`` return garbage lanes
    # (the proven moments-sep pattern) whose computation is row-local and
    # lands in output rows >= ``rows`` — trimmed by the caller. The padded
    # ``sel`` columns are zero, so lane padding in Q is poison-free too.
    return pl.pallas_call(
        functools.partial(_sift_bins_kernel, q_pad=q_pad, variant=variant),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_r, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((w, q_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile_r, NUM_BIN_T * q_pad), lambda i: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (rows_pad, NUM_BIN_T * q_pad), jnp.float32
        ),
        interpret=interpret,
    )(mag2, ang2, sel_p)


def sift_bins_tile(rows: int, width: int, q: int,
                   allow_sweep: bool = True, tier: str = "f32") -> int:
    """Autotuned row-tile height for ``sift.bins`` at this shape bucket —
    and this precision tier: the tier joins the bucket key
    (``autotune.precision_bucket``), so a bf16-swept winner never serves an
    f32 call or vice versa, and the sweep itself times operands of the
    tier's storage dtype. ``allow_sweep=False`` is lookup-only — pass it
    when resolving from inside a trace (a sweep times real executions)."""
    return sift_bins_plan(rows, width, q, allow_sweep=allow_sweep,
                          tier=tier, variant_search=False)[1]


def _sift_validate_args(tier: str):
    key = jax.random.key(11)
    mag = jax.random.uniform(key, (48, 32), jnp.float32)
    ang = jax.random.uniform(key, (48, 32), jnp.float32, -jnp.pi, jnp.pi)
    sel = np.zeros((32, 9), np.float32)
    sel[::3, :] = 1.0
    return mag, ang, sel


def sift_bins_plan(rows: int, width: int, q: int,
                   allow_sweep: bool = True, tier: str = "f32",
                   variant_search: bool = True) -> tuple:
    """``(variant, tile_r)`` for ``sift.bins`` at this bucket/tier: the
    row tile resolves per variant through the autotuner and the measured
    cross-variant winner serves (``variants.search``).
    ``variant_search=False`` restricts to the default (unroll) form — the
    legacy :func:`sift_bins_tile` contract. EAGER-only when sweeping."""
    from keystone_tpu.ops.pallas import variants

    bucket = autotune.precision_bucket(
        autotune.shape_bucket(rows, width), tier
    )
    q_pad = _round_up(max(q, 1), _LANE)
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def measure_for(name):
        def build(tile):
            key = jax.random.key(0)
            mag = jax.random.uniform(key, (rows, width), jnp.float32)
            ang = jax.random.uniform(
                key, (rows, width), jnp.float32, -jnp.pi, jnp.pi
            )
            sel = jnp.zeros((width, q_pad), jnp.float32).at[:, :q].set(1.0)
            interp = default_interpret()
            return lambda i: _sift_bins_pallas(
                (mag + float(i)).astype(in_dtype), ang.astype(in_dtype),
                sel, tile_r=tile, interpret=interp, variant=name,
            )

        return autotune.chained_measure(build)

    def validate_for(name):
        mag, ang, sel = _sift_validate_args(tier)

        def run(variant):
            return sift_oriented_bins(
                mag, ang, sel, tile_r=16, tier=tier, variant=variant
            )

        return variants.validate_variant(
            "sift.bins", name,
            lambda: run(name), lambda: run("unroll"),
            tol=variants.PARITY_TOL[tier],
            program=lambda m, a: sift_oriented_bins(
                m, a, sel, tile_r=16, tier=tier, variant=name
            ),
            program_args=(mag, ang),
        )

    candidates = [t for t in (128, 256, 512, 1024) if t <= max(rows, 128)]
    if not variant_search:
        return "unroll", autotune.resolve(
            "sift.bins", bucket, candidates or [128], 256,
            measure=(
                measure_for("unroll") if allow_sweep else None
            ),
        )
    return variants.search(
        "sift.bins", bucket, candidates or [128], 256,
        measure_for=measure_for, validate_for=validate_for,
        allow_sweep=allow_sweep,
    )


def sift_oriented_bins(mag, angle, sel: np.ndarray, *, tile_r: int = 256,
                       interpret: Optional[bool] = None, tier: str = "f32",
                       variant: str = "unroll"):
    """Fused ``energies @ sel`` without materializing the energies:
    (..., H, W) magnitude/orientation + (W, Q) 0/1 selection matrix ->
    (..., NUM_BIN_T, H, Q). Traceable (called inside the SIFT extractor's
    jit); ``tile_r`` must already be resolved (jit-static). ``tier="bf16"``
    (caller-resolved, like the tile) stores the streamed mag/angle tiles in
    bfloat16 — the kernel upcasts in VMEM and accumulates f32; output is
    always f32. ``variant`` picks the generated kernel form (caller-
    resolved via :func:`sift_bins_plan`, jit-static like the tile)."""
    lead = mag.shape[:-2]
    h, w = mag.shape[-2], mag.shape[-1]
    q = sel.shape[1]
    q_pad = _round_up(max(q, 1), _LANE)
    sel_p = jnp.zeros((w, q_pad), jnp.float32).at[:, :q].set(
        jnp.asarray(sel, jnp.float32)
    )
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32
    rows = int(np.prod(lead, dtype=np.int64)) * h if lead else h
    mag2 = mag.reshape(rows, w).astype(in_dtype)
    ang2 = angle.reshape(rows, w).astype(in_dtype)
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="sift.bins")
    out = _sift_bins_pallas(
        mag2, ang2, sel_p, tile_r=int(tile_r), interpret=bool(interpret),
        variant=str(variant),
    )
    out = out[:rows].reshape(*lead, h, NUM_BIN_T, q_pad)[..., :q]
    return jnp.moveaxis(out, -2, -3)  # (..., T, H, Q)


# ---------------------------------------------------------------------------
# Fisher vector: fused posterior softmax × per-image moment accumulation
# ---------------------------------------------------------------------------
#
# The XLA batch encoder materializes the (n_img, n_desc, k) posterior tensor
# between the log-density gemm and the moment einsums. Per grid step this
# kernel holds one (tile_nd, d) descriptor tile in VMEM, computes its
# posterior rows, and folds them straight into the per-image (k, d)
# accumulators — posteriors never reach HBM. Gradient formulas (the actual
# Fisher encode) are a cheap XLA epilogue over the (n_img, k, d) moments.


def _fv_moments_kernel(
    x_ref, a_ref, b_ref, c_ref, qsum_ref, qx_ref, qx2_ref, *, n_desc: int,
    variant: str = "pair",
):
    j = pl.program_id(1)  # descriptor tile (fastest grid axis)

    @pl.when(j == 0)
    def _():
        qsum_ref[:] = jnp.zeros_like(qsum_ref)
        qx_ref[:] = jnp.zeros_like(qx_ref)
        qx2_ref[:] = jnp.zeros_like(qx2_ref)

    # bf16-input variant: descriptor tiles stream HBM→VMEM in bfloat16
    # under the tier and upcast here — posterior/moment arithmetic always
    # accumulates f32 (no-op astype for f32 inputs: byte-identical)
    x = x_ref[0].astype(jnp.float32)  # (TND, d)
    tile_nd = x.shape[0]
    row_ids = j * tile_nd + jax.lax.broadcasted_iota(
        jnp.int32, (tile_nd, 1), 0
    )
    valid = row_ids < n_desc  # False in the ragged final tile
    x = jnp.where(valid, x, 0.0)  # poison OOB garbage before it hits x**2
    x2 = x * x
    ll = (
        jnp.dot(x, a_ref[:], preferred_element_type=jnp.float32)
        + jnp.dot(x2, b_ref[:], preferred_element_type=jnp.float32)
        + c_ref[:]
    )  # (TND, Kp); padded centers carry c = -1e30 -> softmax ~ 0
    m = jnp.max(ll, axis=1, keepdims=True)
    e = jnp.exp(ll - m)
    q = e / jnp.sum(e, axis=1, keepdims=True)
    q = jnp.where(valid, q, 0.0)  # padded descriptor rows contribute nothing

    qsum_ref[:] += jnp.sum(q, axis=0, keepdims=True)
    qt = q.T  # (Kp, TND)
    if variant == "joint":
        # generated fusion variant: ONE (Kp, TND) @ (TND, 2d) matmul over
        # the concatenated [x, x²] block instead of two d-wide passes —
        # same contractions, twice the MXU width per pass
        d = x.shape[1]
        m = jnp.dot(
            qt, jnp.concatenate([x, x2], axis=1),
            preferred_element_type=jnp.float32,
        )  # (Kp, 2d)
        qx_ref[0] += m[:, :d]
        qx2_ref[0] += m[:, d:]
    else:
        qx_ref[0] += jnp.dot(qt, x, preferred_element_type=jnp.float32)
        qx2_ref[0] += jnp.dot(qt, x2, preferred_element_type=jnp.float32)


@functools.partial(
    jax.jit, static_argnames=("tile_nd", "interpret", "variant")
)
def _fv_moments_pallas(x, A, B, c, *, tile_nd: int, interpret: bool,
                       variant: str = "pair"):
    n_img, nd, d = x.shape
    k_pad = A.shape[1]
    grid = (n_img, pl.cdiv(nd, tile_nd))
    return pl.pallas_call(
        functools.partial(_fv_moments_kernel, n_desc=nd, variant=variant),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, tile_nd, d), lambda i, j: (i, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((d, k_pad), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k_pad), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, k_pad), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, k_pad, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, k_pad, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_img, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_img, k_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((n_img, k_pad, d), jnp.float32),
        ],
        interpret=interpret,
    )(x, A, B, c)


def fv_encode_tile(nd: int, d: int, k: int,
                   allow_sweep: bool = True, tier: str = "f32") -> int:
    """Autotuned descriptor-tile height for ``fv.encode``; the precision
    tier joins the shape bucket (``autotune.precision_bucket``) and the
    sweep times operands of the tier's storage dtype.
    ``allow_sweep=False`` is lookup-only (resolution from inside a
    trace)."""
    return fv_encode_plan(nd, d, k, allow_sweep=allow_sweep, tier=tier,
                          variant_search=False)[1]


def fv_encode_plan(nd: int, d: int, k: int, allow_sweep: bool = True,
                   tier: str = "f32", variant_search: bool = True) -> tuple:
    """``(variant, tile_nd)`` for ``fv.encode``: per-variant tile
    resolution + measured cross-variant winner (``variants.search``).
    ``variant_search=False`` is the legacy default-only contract of
    :func:`fv_encode_tile`. EAGER-only when sweeping."""
    from keystone_tpu.ops.pallas import variants

    bucket = autotune.precision_bucket(autotune.shape_bucket(nd, d, k), tier)
    k_pad = _round_up(max(k, 1), _LANE)
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def measure_for(name):
        def build(tile):
            key = jax.random.key(1)
            x = jax.random.normal(key, (2, nd, d), jnp.float32)
            A = jax.random.normal(key, (d, k_pad), jnp.float32) * 0.1
            B = -jnp.abs(
                jax.random.normal(key, (d, k_pad), jnp.float32)
            ) * 0.1
            c = jnp.zeros((1, k_pad), jnp.float32)
            interp = default_interpret()
            return lambda i: _fv_moments_pallas(
                (x + float(i) * 1e-3).astype(in_dtype), A, B, c,
                tile_nd=tile, interpret=interp, variant=name,
            )

        return autotune.chained_measure(build)

    def validate_for(name):
        key = jax.random.key(12)
        x = jax.random.normal(key, (2, 37, 6), jnp.float32)
        means = jax.random.normal(key, (5, 6), jnp.float32)
        variances = 0.5 + jax.random.uniform(key, (5, 6), jnp.float32)
        weights = jnp.full((5,), 0.2, jnp.float32)

        def run(variant):
            return fv_moments(
                x, means, variances, weights, tile_nd=16, tier=tier,
                variant=variant,
            )

        return variants.validate_variant(
            "fv.encode", name,
            lambda: run(name), lambda: run("pair"),
            tol=variants.PARITY_TOL[tier],
            program=lambda x_: fv_moments(
                x_, means, variances, weights, tile_nd=16, tier=tier,
                variant=name,
            ),
            program_args=(x,),
        )

    candidates = [t for t in (64, 128, 256, 512) if t <= _round_up(nd, 64)]
    if not variant_search:
        return "pair", autotune.resolve(
            "fv.encode", bucket, candidates or [64], 256,
            measure=measure_for("pair") if allow_sweep else None,
        )
    return variants.search(
        "fv.encode", bucket, candidates or [64], 256,
        measure_for=measure_for, validate_for=validate_for,
        allow_sweep=allow_sweep,
    )


def fv_moments(x, means, variances, weights, *, tile_nd: int = 256,
               interpret: Optional[bool] = None, tier: str = "f32",
               variant: str = "pair"):
    """Per-image uncentered GMM moments without HBM posteriors:
    (n_img, nd, d) descriptors -> ``(qsum (n,k), qx (n,k,d), qx2 (n,k,d))``.
    Traceable; the caller resolves ``tile_nd`` eagerly (jit-static). Same
    affine log-density as every other moments path (``_affine_params`` —
    the single source of truth the parity tests pin). ``tier="bf16"``
    streams the descriptor tiles in bfloat16 (the kernel's dominant read);
    GMM parameters, posterior math and the moment accumulators stay f32."""
    from keystone_tpu.ops.pallas.moments import _prep_params

    x = jnp.asarray(x, jnp.float32)
    if tier == "bf16":
        x = x.astype(jnp.bfloat16)
    d = x.shape[2]
    k = means.shape[0]
    k_pad = _round_up(k, _LANE)
    A, B, c = _prep_params(
        jnp.asarray(means, jnp.float32),
        jnp.asarray(variances, jnp.float32),
        jnp.asarray(weights, jnp.float32),
        d, k_pad,
    )
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="fv.encode")
    qsum, qx, qx2 = _fv_moments_pallas(
        x, A, B, c, tile_nd=int(tile_nd), interpret=bool(interpret),
        variant=str(variant),
    )
    return qsum[:, :k], qx[:, :k], qx2[:, :k]


# ---------------------------------------------------------------------------
# Convolver: fused im2col matmul + per-patch normalization
# ---------------------------------------------------------------------------
#
# The XLA twin runs three convolutions (raw, patch-sum, patch-sum-of-
# squares) over the batch and fuses the normalization arithmetic; each conv
# re-reads the image from HBM and the raw result round-trips before the
# epilogue. The kernel holds ONE image in VMEM per grid step, accumulates
# the k² shifted matmuls and the patch statistics in-register, applies the
# normalization and whitener shift, and writes only the finished output
# tile. Filter columns are tiled (``tile_f``) so the accumulator fits VMEM.


def _conv_offsets(ksz: int, loop: str):
    """The k² shifted-matmul visit order — the generated loop-order axis:
    ``"yx"`` (dy-outer, the hand-written form) vs ``"xy"`` (dx-outer).
    Float accumulation order differs, so the two are bit-envelope (not
    bitwise) equivalent — exactly what the variant parity gate checks."""
    if loop == "xy":
        return [(dy, dx) for dx in range(ksz) for dy in range(ksz)]
    return [(dy, dx) for dy in range(ksz) for dx in range(ksz)]


def _conv_norm_body(
    x, f_ref, fsum_ref, mf_ref,
    *, ksz: int, chans: int, res_h: int, res_w: int,
    normalize: bool, var_constant: float, loop: str,
):
    """The convolved + normalized (P, tile_f) block from one VMEM-resident
    image — shared by the ``conv.norm`` kernel and the fused ``conv.pool``
    kernel (the fusion-span variant applies pooling to this block while it
    is still VMEM-resident)."""
    tile_f = f_ref.shape[3]
    p = res_h * res_w
    acc = jnp.zeros((p, tile_f), jnp.float32)
    s1 = jnp.zeros((p, 1), jnp.float32)
    s2 = jnp.zeros((p, 1), jnp.float32)
    for dy, dx in _conv_offsets(ksz, loop):
        xs = x[dy : dy + res_h, dx : dx + res_w, :].reshape(p, chans)
        acc += jnp.dot(
            xs, f_ref[dy, dx], preferred_element_type=jnp.float32
        )
        if normalize:
            s1 += jnp.sum(xs, axis=1, keepdims=True)
            s2 += jnp.sum(xs * xs, axis=1, keepdims=True)
    out = acc
    if normalize:
        n = float(ksz * ksz * chans)
        mean = s1 / n
        var = (s2 - s1 * mean) / (n - 1.0)
        sd = jnp.sqrt(var + var_constant)
        out = (acc - mean * fsum_ref[:]) / sd
    return out - mf_ref[:]


def _conv_norm_kernel(
    x_ref, f_ref, fsum_ref, mf_ref, out_ref,
    *, ksz: int, chans: int, res_h: int, res_w: int,
    normalize: bool, var_constant: float, loop: str = "yx",
):
    # bf16-input streaming (tier axis): the image block arrives in its
    # storage dtype and upcasts IN VMEM; f32 input makes this a no-op
    x = x_ref[0].astype(jnp.float32)  # (H, W, C)
    out_ref[0] = _conv_norm_body(
        x, f_ref, fsum_ref, mf_ref, ksz=ksz, chans=chans, res_h=res_h,
        res_w=res_w, normalize=normalize, var_constant=var_constant,
        loop=loop,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "ksz", "chans", "res_h", "res_w", "normalize", "var_constant",
        "tile_f", "interpret", "variant",
    ),
)
def _conv_norm_pallas(
    imgs, filt, fsum, mf, *, ksz: int, chans: int, res_h: int, res_w: int,
    normalize: bool, var_constant: float, tile_f: int, interpret: bool,
    variant: str = "yx",
):
    n, h, w, _ = imgs.shape
    nf_pad = filt.shape[3]
    grid = (n, nf_pad // tile_f)
    p = res_h * res_w
    out = pl.pallas_call(
        functools.partial(
            _conv_norm_kernel, ksz=ksz, chans=chans, res_h=res_h,
            res_w=res_w, normalize=normalize, var_constant=var_constant,
            loop=variant,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, h, w, chans), lambda i, f: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ksz, ksz, chans, tile_f), lambda i, f: (0, 0, 0, f),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, tile_f), lambda i, f: (0, f), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_f), lambda i, f: (0, f), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, p, tile_f), lambda i, f: (i, 0, f), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, p, nf_pad), jnp.float32),
        interpret=interpret,
    )(imgs, filt, fsum, mf)
    return out


_CONV_VMEM_BUDGET = 12 << 20  # conservative f32 working-set bound per step


def _conv_fits(h: int, w: int, chans: int, ksz: int, tf: int) -> bool:
    res_h, res_w = h - ksz + 1, w - ksz + 1
    p = res_h * res_w
    est = 4 * (
        h * w * chans            # resident image
        + ksz * ksz * chans * tf  # filter tile
        + 3 * p * tf              # acc + epilogue temporaries
        + 2 * p                   # s1 / s2
    )
    return est < _CONV_VMEM_BUDGET


def conv_norm_tile(h: int, w: int, chans: int, ksz: int, nf: int,
                   allow_sweep: bool = True):
    """Autotuned filter-tile width for ``conv.norm``, constrained to tiles
    whose per-step working set fits the VMEM budget. Returns None when no
    candidate fits (caller falls back to the XLA twin).
    ``allow_sweep=False`` is lookup-only."""
    return conv_norm_plan(h, w, chans, ksz, nf, allow_sweep=allow_sweep,
                          variant_search=False)[1]


def _conv_validate_args(tier: str):
    key = jax.random.key(13)
    imgs = jax.random.uniform(key, (2, 11, 13, 3), jnp.float32)
    filters = jax.random.normal(key, (7, 3 * 3 * 3), jnp.float32)
    return imgs, filters


def conv_norm_plan(h: int, w: int, chans: int, ksz: int, nf: int,
                   allow_sweep: bool = True, tier: str = "f32",
                   variant_search: bool = True) -> tuple:
    """``(variant, tile_f)`` for ``conv.norm`` — ``(variant, None)`` when
    no tile fits the VMEM budget (caller falls back to the XLA twin).
    ``variant_search=False`` restricts to the default dy-outer loop order
    (the :func:`conv_norm_tile` contract). EAGER-only when sweeping."""
    from keystone_tpu.ops.pallas import variants

    candidates = [
        t for t in (64, 128, 256, 512) if _conv_fits(h, w, chans, ksz, t)
    ]
    if not candidates:
        _count("fallback", kernel="conv.norm", reason="vmem")
        return "yx", None
    res_h, res_w = h - ksz + 1, w - ksz + 1
    bucket = autotune.precision_bucket(
        autotune.shape_bucket(h, w, nf), tier
    )
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def measure_for(name):
        def build(tile):
            key = jax.random.key(2)
            xi = jax.random.uniform(key, (2, h, w, chans), jnp.float32)
            nf_pad = _round_up(nf, tile)
            fi = jax.random.normal(
                key, (ksz, ksz, chans, nf_pad), jnp.float32
            )
            fs = jnp.sum(fi.reshape(-1, nf_pad), axis=0, keepdims=True)
            mfz = jnp.zeros((1, nf_pad), jnp.float32)
            args = dict(
                ksz=ksz, chans=chans, res_h=res_h, res_w=res_w,
                normalize=True, var_constant=10.0, tile_f=tile,
                interpret=default_interpret(), variant=name,
            )
            return lambda i: _conv_norm_pallas(
                (xi + float(i) * 1e-3).astype(in_dtype), fi, fs, mfz,
                **args
            )

        return autotune.chained_measure(build)

    def validate_for(name):
        imgs, filters = _conv_validate_args(tier)

        def run(variant):
            return conv_norm(
                imgs, filters, num_channels=3, normalize=True,
                var_constant=10.0, tile_f=64, tier=tier, variant=variant,
            )

        return variants.validate_variant(
            "conv.norm", name,
            lambda: run(name), lambda: run("yx"),
            tol=variants.PARITY_TOL[tier],
            program=lambda im: conv_norm(
                im, filters, num_channels=3, normalize=True,
                var_constant=10.0, tile_f=64, tier=tier, variant=name,
            ),
            program_args=(imgs,),
        )

    if not variant_search:
        return "yx", autotune.resolve(
            "conv.norm", bucket, candidates, candidates[0],
            measure=measure_for("yx") if allow_sweep else None,
        )
    return variants.search(
        "conv.norm", bucket, candidates, candidates[0],
        measure_for=measure_for, validate_for=validate_for,
        allow_sweep=allow_sweep,
    )


def conv_norm(imgs, filters, *, num_channels: int, normalize: bool,
              var_constant: float, whitener_means=None, tile_f: int = 128,
              interpret: Optional[bool] = None, tier: str = "f32",
              variant: str = "yx"):
    """Fused Convolver forward: (N, H, W, C) images + (nF, k·k·C) filters
    (reference patch layout) -> (N, resH, resW, nF). Traceable; ``tile_f``
    and ``variant`` pre-resolved via :func:`conv_norm_plan`. ``tier="bf16"``
    streams the image blocks in bfloat16 (the kernel upcasts in VMEM);
    filters and all accumulation stay f32."""
    imgs = jnp.asarray(imgs, jnp.float32)
    if tier == "bf16":
        imgs = imgs.astype(jnp.bfloat16)
    n, h, w, c = imgs.shape
    nf = filters.shape[0]
    k2 = filters.shape[1] // num_channels
    ksz = int(round(k2**0.5))
    res_h, res_w = h - ksz + 1, w - ksz + 1
    tile_f = int(tile_f)
    nf_pad = _round_up(nf, tile_f)
    filt = jnp.zeros((nf_pad, ksz * ksz * c), jnp.float32).at[:nf].set(
        jnp.asarray(filters, jnp.float32)
    )
    # padded filters are all-zero -> their output columns are exactly
    # -mf_pad = 0 after the normalization arithmetic; trimmed below anyway
    filt = filt.reshape(nf_pad, ksz, ksz, c).transpose(1, 2, 3, 0)
    fsum = jnp.sum(filt.reshape(-1, nf_pad), axis=0, keepdims=True)
    mf = jnp.zeros((1, nf_pad), jnp.float32)
    if whitener_means is not None:
        mf = mf.at[:, :nf].set(
            (jnp.asarray(whitener_means, jnp.float32) @ filters.T)[None]
        )
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="conv.norm")
    out = _conv_norm_pallas(
        imgs, filt, fsum, mf, ksz=ksz, chans=c, res_h=res_h, res_w=res_w,
        normalize=bool(normalize), var_constant=float(var_constant),
        tile_f=tile_f, interpret=bool(interpret), variant=str(variant),
    )
    return out.reshape(n, res_h, res_w, nf_pad)[..., :nf]


# ---------------------------------------------------------------------------
# Pooler: fused pixel-function + separable sum-pool selection matmuls
# ---------------------------------------------------------------------------
#
# Sum pooling over clamped windows is separable into two 0/1 selection
# matmuls (the ``_bin_select_matrix`` trick): out = Myᵀ · f(img) · Mx per
# channel. The kernel applies the elementwise pixel function and both
# contractions in VMEM, so the f(img) intermediate never reaches HBM.
# Max pooling is not a matmul; it stays on the XLA reduce_window twin.


def pool_select_matrix(dim: int, stride: int, pool_size: int) -> np.ndarray:
    """(dim, num_pools) 0/1 matrix: column p sums pixels
    [p·stride, p·stride + pool_size) ∩ [0, dim) — the clamped windows of
    ``Pooler`` (``_pool_geometry``), exactly (clamping = missing rows)."""
    stride_start = pool_size // 2
    num_pools = -(-(dim - stride_start) // stride)
    m = np.zeros((dim, num_pools), np.float32)
    for pi in range(num_pools):
        lo = pi * stride
        hi = min(lo + pool_size, dim)
        m[lo:hi, pi] = 1.0
    return m


def _pool_contract(y, my, mx, *, order: str):
    """Both separable contractions applied to one (H, W, TC) block in VMEM
    — shared by the ``pool.sum`` kernel and the fused ``conv.pool`` kernel.
    ``order`` is the generated contraction-order axis: ``"hw"`` (H-axis
    first, the hand-written form) vs ``"wh"`` (W-axis first); the sums are
    associatively regrouped, so the two forms are bit-envelope (not
    bitwise) equivalent."""
    h, w, tc = y.shape
    p = my.shape[1]
    q = mx.shape[1]
    if order == "wh":
        # contract W first: (H·TC, W) @ (W, Q), then H: (P, H) @ (H, TC·Q)
        t1 = jnp.dot(
            jnp.transpose(y, (0, 2, 1)).reshape(h * tc, w), mx,
            preferred_element_type=jnp.float32,
        ).reshape(h, tc, q)
        t2 = jnp.dot(
            my.T, t1.reshape(h, tc * q), preferred_element_type=jnp.float32
        ).reshape(p, tc, q)
        return jnp.transpose(t2, (0, 2, 1))  # (P, Q, TC)
    # contract H: (P, H) @ (H, W·TC) — one clean 2D matmul
    t1 = jnp.dot(
        my.T, y.reshape(h, w * tc), preferred_element_type=jnp.float32
    ).reshape(p, w, tc)
    # contract W: regroup channels-major so the second contraction is 2D too
    t2 = jnp.dot(
        jnp.transpose(t1, (0, 2, 1)).reshape(p * tc, w),
        mx,
        preferred_element_type=jnp.float32,
    ).reshape(p, tc, q)
    return jnp.transpose(t2, (0, 2, 1))  # (P, Q, TC)


def _pool_sum_kernel(x_ref, my_ref, mx_ref, out_ref, *, pixel_fn,
                     order: str = "hw"):
    # bf16-input streaming (tier axis): upcast in VMEM; no-op for f32
    y = x_ref[0].astype(jnp.float32)  # (H, W, TC)
    if pixel_fn is not None:
        y = pixel_fn(y)
    out_ref[0] = _pool_contract(y, my_ref[:], mx_ref[:], order=order)


@functools.partial(
    jax.jit, static_argnames=("pixel_fn", "tile_c", "interpret", "variant")
)
def _pool_sum_pallas(imgs, my, mx, *, pixel_fn, tile_c: int, interpret: bool,
                     variant: str = "hw"):
    n, h, w, c_pad = imgs.shape
    p, q = my.shape[1], mx.shape[1]
    grid = (n, c_pad // tile_c)
    return pl.pallas_call(
        functools.partial(_pool_sum_kernel, pixel_fn=pixel_fn, order=variant),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, h, w, tile_c), lambda i, cc: (i, 0, 0, cc),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((h, p), lambda i, cc: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((w, q), lambda i, cc: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, p, q, tile_c), lambda i, cc: (i, 0, 0, cc),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, p, q, c_pad), jnp.float32),
        interpret=interpret,
    )(imgs, my, mx)


_POOL_VMEM_BUDGET = 8 << 20  # f32 bound on the per-step input block


def pool_block_fits(h: int, w: int, c: int) -> bool:
    """Whether one (H, W, c) f32 block fits the pool kernel's VMEM budget
    — the eligibility bound for the untiled (pixel-function) form."""
    return 4 * h * w * c < _POOL_VMEM_BUDGET


def pool_sum_tile(h: int, w: int, c: int):
    """Autotuned channel-tile width for ``pool.sum``, or None when no
    candidate fits the VMEM budget (caller falls back to the XLA twin —
    the same contract as :func:`conv_norm_tile`). EAGER-only."""
    return pool_sum_plan(h, w, c, allow_sweep=False,
                         variant_search=False)[1]


def pool_sum_plan(h: int, w: int, c: int, *, stride: int = 2,
                  pool_size: int = 2, allow_sweep: bool = True,
                  tier: str = "f32", variant_search: bool = True) -> tuple:
    """``(variant, tile_c)`` for ``pool.sum`` — ``(variant, None)`` when no
    channel tile fits the VMEM budget. The PR-7 tile path never swept this
    kernel (``measure=None``); the variant search gives it a real measure
    builder, so under ``KEYSTONE_AUTOTUNE=1`` both the contraction order
    AND the channel tile are now measured. ``stride``/``pool_size`` shape
    the timed pooling geometry only — they do not join the bucket.
    EAGER-only when sweeping."""
    from keystone_tpu.ops.pallas import variants

    candidates = [
        t for t in (64, 128, 256, 512) if pool_block_fits(h, w, t)
    ]
    if not candidates:
        _count("fallback", kernel="pool.sum", reason="vmem")
        return "hw", None
    bucket = autotune.precision_bucket(autotune.shape_bucket(h, w, c), tier)
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def measure_for(name):
        def build(tile):
            key = jax.random.key(3)
            xi = jax.random.uniform(key, (2, h, w, tile), jnp.float32)
            my = jnp.asarray(pool_select_matrix(h, stride, pool_size))
            mx = jnp.asarray(pool_select_matrix(w, stride, pool_size))
            interp = default_interpret()
            return lambda i: _pool_sum_pallas(
                (xi + float(i) * 1e-3).astype(in_dtype), my, mx,
                pixel_fn=None, tile_c=tile, interpret=interp, variant=name,
            )

        return autotune.chained_measure(build)

    def validate_for(name):
        key = jax.random.key(14)
        imgs = jax.random.uniform(key, (2, 9, 11, 5), jnp.float32)

        def run(variant):
            return pool_sum(imgs, 2, 3, None, tile_c=64, tier=tier,
                            variant=variant)

        return variants.validate_variant(
            "pool.sum", name,
            lambda: run(name), lambda: run("hw"),
            tol=variants.PARITY_TOL[tier],
            program=lambda im: pool_sum(
                im, 2, 3, None, tile_c=64, tier=tier, variant=name
            ),
            program_args=(imgs,),
        )

    if not variant_search:
        return "hw", autotune.resolve(
            "pool.sum", bucket, candidates, candidates[0],
            measure=measure_for("hw") if allow_sweep else None,
        )
    return variants.search(
        "pool.sum", bucket, candidates, candidates[0],
        measure_for=measure_for, validate_for=validate_for,
        allow_sweep=allow_sweep,
    )


def pool_sum(imgs, stride: int, pool_size: int,
             pixel_fn: Optional[Callable] = None, *, tile_c: int = 128,
             interpret: Optional[bool] = None, tier: str = "f32",
             variant: str = "hw"):
    """Fused sum-Pooler forward over a batch: (N, H, W, C) -> (N, P, Q, C).
    ``pixel_fn`` must be shape/dtype-preserving (checked by the caller via
    ``eval_shape``); when one is present the kernel never tiles or pads
    the channel axis — each grid step hands the function the FULL
    (H, W, C) block, so even a channel-mixing function stays correct.
    ``tier="bf16"`` streams the image blocks in bfloat16 (upcast in VMEM
    before the pixel function); ``variant`` is the contraction order
    (caller-resolved via :func:`pool_sum_plan`, jit-static)."""
    imgs = jnp.asarray(imgs, jnp.float32)
    if tier == "bf16":
        imgs = imgs.astype(jnp.bfloat16)
    n, h, w, c = imgs.shape
    if pixel_fn is not None:
        tile_c = c_pad = c
    else:
        tile_c = int(min(tile_c, _round_up(c, 8)))
        c_pad = _round_up(c, tile_c)
    if c_pad != c:
        imgs = jnp.pad(imgs, ((0, 0), (0, 0), (0, 0), (0, c_pad - c)))
    my = jnp.asarray(pool_select_matrix(h, stride, pool_size))
    mx = jnp.asarray(pool_select_matrix(w, stride, pool_size))
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="pool.sum")
    out = _pool_sum_pallas(
        imgs, my, mx, pixel_fn=pixel_fn, tile_c=tile_c,
        interpret=bool(interpret), variant=str(variant),
    )
    return out[..., :c]


# ---------------------------------------------------------------------------
# Fused conv.norm → pool.sum: the fusion-span variant
# ---------------------------------------------------------------------------
#
# The split pair writes the normalized (N, resH, resW, nF) conv output to
# HBM and immediately re-reads it for pooling — at CIFAR scale that tensor
# is the largest intermediate in the featurization chain. The fused kernel
# reuses ``_conv_norm_body``'s (P, tile_f) block while it is still
# VMEM-resident: reshape to (resH, resW, tile_f), apply both separable
# pooling contractions (``_pool_contract``), and write only the pooled
# (P', Q', tile_f) tile. The conv intermediate NEVER touches HBM. Padded
# filter columns stay exact zeros through normalization and pooling (sums
# of zeros), so the trailing trim is unchanged.


def _conv_pool_kernel(
    x_ref, f_ref, fsum_ref, mf_ref, my_ref, mx_ref, out_ref,
    *, ksz: int, chans: int, res_h: int, res_w: int,
    normalize: bool, var_constant: float, loop: str,
):
    x = x_ref[0].astype(jnp.float32)  # (H, W, C); bf16 tier upcasts here
    conv = _conv_norm_body(
        x, f_ref, fsum_ref, mf_ref, ksz=ksz, chans=chans, res_h=res_h,
        res_w=res_w, normalize=normalize, var_constant=var_constant,
        loop=loop,
    )  # (P, tile_f) — still VMEM-resident
    y = conv.reshape(res_h, res_w, f_ref.shape[3])
    out_ref[0] = _pool_contract(y, my_ref[:], mx_ref[:], order="hw")


@functools.partial(
    jax.jit,
    static_argnames=(
        "ksz", "chans", "res_h", "res_w", "normalize", "var_constant",
        "tile_f", "interpret", "loop",
    ),
)
def _conv_pool_pallas(
    imgs, filt, fsum, mf, my, mx, *, ksz: int, chans: int, res_h: int,
    res_w: int, normalize: bool, var_constant: float, tile_f: int,
    interpret: bool, loop: str,
):
    n, h, w, _ = imgs.shape
    nf_pad = filt.shape[3]
    p, q = my.shape[1], mx.shape[1]
    grid = (n, nf_pad // tile_f)
    return pl.pallas_call(
        functools.partial(
            _conv_pool_kernel, ksz=ksz, chans=chans, res_h=res_h,
            res_w=res_w, normalize=normalize, var_constant=var_constant,
            loop=loop,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, h, w, chans), lambda i, f: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ksz, ksz, chans, tile_f), lambda i, f: (0, 0, 0, f),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, tile_f), lambda i, f: (0, f), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_f), lambda i, f: (0, f), memory_space=pltpu.VMEM),
            pl.BlockSpec((res_h, p), lambda i, f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((res_w, q), lambda i, f: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, p, q, tile_f), lambda i, f: (i, 0, 0, f),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, p, q, nf_pad), jnp.float32),
        interpret=interpret,
    )(imgs, filt, fsum, mf, my, mx)


def _conv_pool_fits(h: int, w: int, chans: int, ksz: int,
                    stride: int, pool_size: int, tf: int) -> bool:
    """The fused step's working set: conv's bound plus the pool matrices
    and the pooled temporaries."""
    res_h, res_w = h - ksz + 1, w - ksz + 1
    p_out = pool_select_matrix(res_h, stride, pool_size).shape[1]
    q_out = pool_select_matrix(res_w, stride, pool_size).shape[1]
    extra = 4 * (
        res_h * p_out + res_w * q_out   # selection matrices
        + 2 * p_out * res_w * tf        # t1 + its regrouped copy
        + 2 * p_out * q_out * tf        # t2 + output tile
    )
    return _conv_fits(h, w, chans, ksz, tf) and (
        4 * (h * w * chans + ksz * ksz * chans * tf + 3 * res_h * res_w * tf)
        + extra < _CONV_VMEM_BUDGET
    )


def conv_norm_pool(imgs, filters, *, num_channels: int, normalize: bool,
                   var_constant: float, stride: int, pool_size: int,
                   whitener_means=None, tile_f: int = 128,
                   interpret: Optional[bool] = None, tier: str = "f32",
                   variant: str = "split"):
    """The fusion-span entry point: Convolver forward + sum pooling,
    (N, H, W, C) -> (N, P, Q, nF). ``variant="split"`` composes the
    :func:`conv_norm` and :func:`pool_sum` kernels through HBM (the
    reference pair, and the form the autotuner times as the incumbent);
    ``"fused.yx"``/``"fused.xy"`` run ONE kernel whose conv block stays
    VMEM-resident through normalization and pooling — the suffix is the
    conv loop order (:func:`_conv_offsets`). Traceable; ``tile_f`` and
    ``variant`` pre-resolved via :func:`conv_pool_plan`."""
    if variant == "split":
        conv = conv_norm(
            imgs, filters, num_channels=num_channels, normalize=normalize,
            var_constant=var_constant, whitener_means=whitener_means,
            tile_f=tile_f, interpret=interpret, tier=tier,
        )
        return pool_sum(
            conv, stride, pool_size, None, tile_c=min(int(tile_f), 512),
            interpret=interpret, tier=tier,
        )
    loop = variant.split(".", 1)[1]  # "fused.yx" -> "yx"
    imgs = jnp.asarray(imgs, jnp.float32)
    if tier == "bf16":
        imgs = imgs.astype(jnp.bfloat16)
    n, h, w, c = imgs.shape
    nf = filters.shape[0]
    k2 = filters.shape[1] // num_channels
    ksz = int(round(k2**0.5))
    res_h, res_w = h - ksz + 1, w - ksz + 1
    tile_f = int(tile_f)
    nf_pad = _round_up(nf, tile_f)
    filt = jnp.zeros((nf_pad, ksz * ksz * c), jnp.float32).at[:nf].set(
        jnp.asarray(filters, jnp.float32)
    )
    filt = filt.reshape(nf_pad, ksz, ksz, c).transpose(1, 2, 3, 0)
    fsum = jnp.sum(filt.reshape(-1, nf_pad), axis=0, keepdims=True)
    mf = jnp.zeros((1, nf_pad), jnp.float32)
    if whitener_means is not None:
        mf = mf.at[:, :nf].set(
            (jnp.asarray(whitener_means, jnp.float32) @ filters.T)[None]
        )
    my = jnp.asarray(pool_select_matrix(res_h, stride, pool_size))
    mx = jnp.asarray(pool_select_matrix(res_w, stride, pool_size))
    if interpret is None:
        interpret = default_interpret()
    _count("engaged", kernel="conv.pool")
    out = _conv_pool_pallas(
        imgs, filt, fsum, mf, my, mx, ksz=ksz, chans=c, res_h=res_h,
        res_w=res_w, normalize=bool(normalize),
        var_constant=float(var_constant), tile_f=tile_f,
        interpret=bool(interpret), loop=loop,
    )
    return out[..., :nf]


def _conv_pool_validate_args(tier: str):
    key = jax.random.key(15)
    imgs = jax.random.uniform(key, (2, 11, 13, 3), jnp.float32)
    filters = jax.random.normal(key, (7, 3 * 3 * 3), jnp.float32)
    return imgs, filters


def conv_pool_plan(h: int, w: int, chans: int, ksz: int, nf: int, *,
                   stride: int, pool_size: int, allow_sweep: bool = True,
                   tier: str = "f32", variant_search: bool = True) -> tuple:
    """``(variant, tile_f)`` for the conv→pool span — ``("split", None)``
    when no tile fits even the split conv budget (caller falls back to the
    XLA twins). The "split" default's cache entry times the REAL two-kernel
    pipeline (conv through HBM, then pool), so a fused win is an honest
    end-to-end win, never an artifact of timing half the work. Fused
    candidates are additionally bounded by :func:`_conv_pool_fits`.
    EAGER-only when sweeping."""
    from keystone_tpu.ops.pallas import variants

    candidates = [
        t for t in (64, 128, 256, 512) if _conv_fits(h, w, chans, ksz, t)
    ]
    if not candidates:
        _count("fallback", kernel="conv.pool", reason="vmem")
        return "split", None
    fused_candidates = [
        t for t in candidates
        if _conv_pool_fits(h, w, chans, ksz, stride, pool_size, t)
    ]
    res_h, res_w = h - ksz + 1, w - ksz + 1
    bucket = autotune.precision_bucket(
        autotune.shape_bucket(h, w, nf), tier
    )
    in_dtype = jnp.bfloat16 if tier == "bf16" else jnp.float32

    def measure_for(name):
        def build(tile):
            key = jax.random.key(4)
            xi = jax.random.uniform(key, (2, h, w, chans), jnp.float32)
            fi = jax.random.normal(
                key, (nf, ksz * ksz * chans), jnp.float32
            )
            args = dict(
                num_channels=chans, normalize=True, var_constant=10.0,
                stride=stride, pool_size=pool_size, tile_f=tile,
                interpret=default_interpret(), tier=tier, variant=name,
            )
            return lambda i: conv_norm_pool(
                (xi + float(i) * 1e-3).astype(in_dtype), fi, **args
            )

        return autotune.chained_measure(build)

    def validate_for(name):
        imgs, filters = _conv_pool_validate_args(tier)

        def run(variant):
            return conv_norm_pool(
                imgs, filters, num_channels=3, normalize=True,
                var_constant=10.0, stride=2, pool_size=3, tile_f=64,
                tier=tier, variant=variant,
            )

        return variants.validate_variant(
            "conv.pool", name,
            lambda: run(name), lambda: run("split"),
            tol=variants.PARITY_TOL[tier],
            program=lambda im: conv_norm_pool(
                im, filters, num_channels=3, normalize=True,
                var_constant=10.0, stride=2, pool_size=3, tile_f=64,
                tier=tier, variant=name,
            ),
            program_args=(imgs,),
        )

    def validate_gate(name):
        # fused candidates must also FIT: a fused variant whose working
        # set overflows the budget at every tile is skipped, not swept
        if name.startswith("fused.") and not fused_candidates:
            return False
        return validate_for(name)

    if not variant_search:
        return "split", autotune.resolve(
            "conv.pool", bucket, candidates, candidates[0],
            measure=measure_for("split") if allow_sweep else None,
        )
    return variants.search(
        "conv.pool", bucket, candidates, candidates[0],
        measure_for=measure_for, validate_for=validate_gate,
        allow_sweep=allow_sweep,
    )
