"""DAISY descriptors.

Reference: ``nodes/images/DaisyExtractor.scala:28-201`` — gradients via
``conv2D`` with [1,0,-1]/[1,2,1] (``:110-111``), H=8 oriented half-rectified
gradient maps, Q=3 layers of cumulative Gaussian blurs with
σ²-differences derived from the ring radii (``:116-135``), per-keypoint
histograms read at ring offsets (radius (l+1)·R/Q, angle 2π(t−1)/T) and
L2-normalized with a zero threshold (``:152-200``). Feature size
H·(T·Q+1) = 200 with the reference's exact layout (center block first).
"""

from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.ops.images.lcs import conv2d_same

_FEATURE_THRESHOLD = 1e-8
_CONV_THRESHOLD = 1e-6


def _daisy_gaussians(daisy_q: int, daisy_r: int) -> List[np.ndarray]:
    """The reference's unnormalized incremental Gaussian kernels
    (``DaisyExtractor.scala:50-63``)."""
    sigma_sq = [(daisy_r * n / (2.0 * daisy_q)) ** 2 for n in range(daisy_q + 1)]
    diffs = [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]
    kernels = []
    for t in diffs:
        radius = int(
            math.ceil(math.sqrt(-2 * t * math.log(_CONV_THRESHOLD) - t * math.log(2 * math.pi * t)))
        )
        n = np.arange(-radius, radius + 1, dtype=np.float64)
        kernels.append(
            (np.exp(-(n**2) / (2 * t)) / math.sqrt(2 * math.pi * t)).astype(np.float32)
        )
    return kernels


class DaisyExtractor(Transformer):
    daisy_t: int = struct.field(pytree_node=False, default=8)
    daisy_q: int = struct.field(pytree_node=False, default=3)
    daisy_r: int = struct.field(pytree_node=False, default=7)
    daisy_h: int = struct.field(pytree_node=False, default=8)
    pixel_border: int = struct.field(pytree_node=False, default=16)
    stride: int = struct.field(pytree_node=False, default=4)
    patch_size: int = struct.field(pytree_node=False, default=24)

    @property
    def feature_size(self) -> int:
        return self.daisy_h * (self.daisy_t * self.daisy_q + 1)

    def apply(self, img):
        """(H, W) or (H, W, 1) grayscale -> (num_keypoints, H·(T·Q+1))."""
        if img.ndim == 3:
            img = img[..., 0]
        h, w = img.shape
        T, Q, R, H = self.daisy_t, self.daisy_q, self.daisy_r, self.daisy_h

        f1 = np.array([1.0, 0.0, -1.0], np.float32)
        f2 = np.array([1.0, 2.0, 1.0], np.float32)
        # ref: ix = conv2D(in, f1, f2) — ref xFilter runs along ref-x, which
        # is our axis 0, i.e. conv2d_same's y_filter slot
        ix = conv2d_same(img, f2, f1)
        iy = conv2d_same(img, f1, f2)

        angles = 2.0 * jnp.pi * jnp.arange(H) / H
        oriented = jnp.maximum(
            jnp.cos(angles)[:, None, None] * ix + jnp.sin(angles)[:, None, None] * iy,
            0.0,
        )  # (H, h, w)

        kernels = _daisy_gaussians(Q, R)
        layers = []
        cur = oriented
        for q in range(Q):
            cur = conv2d_same(cur, kernels[q], kernels[q])
            layers.append(cur)  # cumulative blurs

        kys = jnp.arange(self.pixel_border, h - self.pixel_border, self.stride)
        kxs = jnp.arange(self.pixel_border, w - self.pixel_border, self.stride)
        ny, nx = kys.shape[0], kxs.shape[0]

        def normalize(hists):
            """L2-normalize histogram vectors on the last axis, zeroing those
            below the threshold (``DaisyExtractor.scala:193-200``)."""
            nrm = jnp.linalg.norm(hists, axis=-1, keepdims=True)
            return jnp.where(nrm > _FEATURE_THRESHOLD, hists / jnp.maximum(nrm, 1e-30), 0.0)

        # center histogram: layer 0 at the keypoint
        center = layers[0][:, kys, :][:, :, kxs]  # (H, ny, nx)
        center = normalize(center.transpose(1, 2, 0))  # (ny, nx, H)

        # ring histograms: layer l at radius (l+1)R/Q, angle 2π(t-1)/T.
        # ref: lookupStartX = x + round(r·sinθ), lookupStartY = y + round(r·cosθ),
        # and ref-x IS our axis 0 (Image.scala:139: xDim is the height)
        ring_blocks = []
        for t in range(T):
            theta = 2.0 * math.pi * (t - 1) / T
            for l in range(Q):
                rad = R * (1.0 + l) / Q
                o0 = int(round(rad * math.sin(theta)))  # ref-x -> axis 0
                o1 = int(round(rad * math.cos(theta)))  # ref-y -> axis 1
                hist = layers[l][:, kys + o0, :][:, :, kxs + o1]  # (H, ny, nx)
                ring_blocks.append(normalize(hist.transpose(1, 2, 0)))

        # layout: center at [0, H), ring block (t, l) at H + t*Q*H + l*H —
        # exactly [center] + ring_blocks (t outer, l inner) concatenated
        out = jnp.concatenate([center] + ring_blocks, axis=-1)
        # reference row order: x*resultWidth + y with ref-x = our axis 0 —
        # a plain row-major reshape
        return out.reshape(ny * nx, self.feature_size)
