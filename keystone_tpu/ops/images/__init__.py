from keystone_tpu.ops.images.nodes import (
    GrayScaler,
    ImageExtractor,
    ImageVectorizer,
    LabelExtractor,
    MultiLabelExtractor,
    MultiLabeledImageExtractor,
    PixelScaler,
    SymmetricRectifier,
)
from keystone_tpu.ops.images.image_utils import (
    conv2d_same,
    map_pixels,
    pixel_combine,
    split_channels,
    to_grayscale,
)
from keystone_tpu.ops.images.convolver import Convolver
from keystone_tpu.ops.images.pooler import Pooler
from keystone_tpu.ops.images.windower import Windower
from keystone_tpu.ops.images.fisher_vector import FisherVector
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.hog import HogExtractor
from keystone_tpu.ops.images.daisy import DaisyExtractor
