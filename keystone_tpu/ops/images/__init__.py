from keystone_tpu.ops.images.nodes import (
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    SymmetricRectifier,
)
from keystone_tpu.ops.images.convolver import Convolver
from keystone_tpu.ops.images.pooler import Pooler
from keystone_tpu.ops.images.windower import Windower
