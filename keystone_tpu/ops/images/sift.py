"""Dense multi-scale SIFT, XLA-native.

Replaces the reference's JNI/vlfeat path
(``nodes/images/external/SIFTExtractor.scala:16-57`` →
``src/main/cpp/VLFeat.cxx:37-292``), which emulates ``vl_phow``:

per scale s in 0..num_scales-1:
  - bin_s  = bin_size + 2s                    (``VLFeat.cxx:75``)
  - smooth the ORIGINAL image, σ = bin_s / 6  (magnif=6, ``VLFeat.cxx:85-90``)
  - dsift with step_s = step + s·scale_step   (``VLFeat.cxx:77``)
  - bounds aligned across scales: min = (1+2·num_scales) − 3s, max = dim−1
    (``VLFeat.cxx:93-95``)
  - flat window (box spatial bins), window size 1.5 (``VLFeat.cxx:98-102``)
  - descriptors with gradient mass < 0.005 are zeroed (``VLFeat.cxx:62,143``)
  - vl transpose layout + quantize min(512·v, 255) (``VLFeat.cxx:256-263``)

Algorithm (vl_dsift, flat-window formulation): gradient magnitude m and
orientation θ per pixel; bilinear binning of θ into 8 orientation energy
maps; per spatial bin, a box filter of width bin_s centered on the bin
center aggregates each energy map (the flat-window approximation of the
triangular×Gaussian weighting — same total mass, since ∫tri = bin_s =
∫box); 4×4 spatial bins × 8 orientations sampled on the keypoint grid;
L2-normalize, clamp at 0.2, renormalize.

Everything is expressed as convolutions/reduce_windows + one gather, so a
whole batch of images compiles to a handful of fused XLA ops on the MXU/VPU.
Exact bitwise vlfeat parity is not possible here (no vlfeat binary for this
platform exists in the environment); the implementation follows the
documented algorithm and is tested against an independent naive oracle.

Descriptors are returned (num_keypoints, 128) row-major (the reference
returns the 128×N transpose).
"""

from __future__ import annotations

import functools
import math
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer

NUM_BIN_T = 8  # orientation bins
NUM_BIN_S = 4  # spatial bins per axis
DESC_DIM = NUM_BIN_T * NUM_BIN_S * NUM_BIN_S  # 128
CONTRAST_THRESHOLD = 0.005


def _gaussian_blur(img, sigma: float):
    """Separable Gaussian smoothing with replicate (continuity) padding,
    kernel truncated at 4σ like vl_imsmooth. Runs as banded-matrix matmuls
    on small axes (``image_utils._conv1d_same``) — the symmetric kernel is
    its own flip, so the true-convolution contract is the correlation the
    reference computes."""
    if sigma <= 0:
        return img
    radius = max(1, int(math.ceil(4.0 * sigma)))
    t = np.arange(-radius, radius + 1, dtype=np.float32)
    k = np.exp(-0.5 * (t / sigma) ** 2)
    k /= k.sum()
    from keystone_tpu.ops.images.image_utils import _conv1d_same

    return _conv1d_same(
        _conv1d_same(img, k, -1, mode="edge"), k, -2, mode="edge"
    )


def _gradient_polar(img):
    """np.gradient-style central differences (one-sided at borders), then
    magnitude/orientation — the vl_imgradient_polar_f contract."""
    gy = jnp.gradient(img, axis=-2)
    gx = jnp.gradient(img, axis=-1)
    mag = jnp.sqrt(gx * gx + gy * gy)
    angle = jnp.arctan2(gy, gx)
    return mag, angle


def _orientation_energies(mag, angle):
    """Bilinear binning into NUM_BIN_T orientation maps: (..., H, W) ->
    (..., T, H, W)."""
    ft = (angle / (2.0 * jnp.pi)) * NUM_BIN_T
    ft = jnp.mod(ft, NUM_BIN_T)
    bins = jnp.arange(NUM_BIN_T, dtype=jnp.float32)
    d = jnp.mod(ft[..., None, :, :] - bins[:, None, None], NUM_BIN_T)
    w = jnp.maximum(0.0, 1.0 - d) + jnp.maximum(0.0, d - (NUM_BIN_T - 1))
    return mag[..., None, :, :] * w


def _box_sums(energies, bin_size: int):
    """Box-filter sums of width bin_size (stride 1, VALID): output index j
    covers pixels [j, j+bin_size). The PRODUCTION bin-aggregation path on
    non-TPU backends (``_dsift_single_scale`` impl="auto"/"window"); on TPU
    it is fused with the keypoint gather into selection matmuls
    (``_bin_select_matrix``) instead."""
    return jax.lax.reduce_window(
        energies,
        0.0,
        jax.lax.add,
        window_dimensions=(1,) * (energies.ndim - 2) + (bin_size, bin_size),
        window_strides=(1,) * energies.ndim,
        padding="VALID",
    )


def dsift_geometry(
    width: int, height: int, step: int, bin_size: int, min_bound: int
) -> Tuple[int, int]:
    """vl_dsift keypoint counts: numFrames = (range // step) + 1 with
    range = (max - min) - binSize·(numBins-1), per axis."""
    range_x = (width - 1 - min_bound) - bin_size * (NUM_BIN_S - 1)
    range_y = (height - 1 - min_bound) - bin_size * (NUM_BIN_S - 1)
    nx = range_x // step + 1 if range_x >= 0 else 0
    ny = range_y // step + 1 if range_y >= 0 else 0
    return ny, nx


def _transpose_descriptor_layout() -> np.ndarray:
    """vl_dsift_transpose_descriptor permutation: swap x/y spatial bins and
    flip the orientation index (t' = (8-t) mod 8) — the MATLAB-compatible
    layout the reference emits (``VLFeat.cxx:256``)."""
    perm = np.zeros(DESC_DIM, dtype=np.int32)
    for y in range(NUM_BIN_S):
        for x in range(NUM_BIN_S):
            for t in range(NUM_BIN_T):
                src = t + NUM_BIN_T * (x + NUM_BIN_S * y)
                flipped = (NUM_BIN_T - t) % NUM_BIN_T
                dst = flipped + NUM_BIN_T * (y + NUM_BIN_S * x)
                perm[dst] = src
    return perm


_TRANSPOSE_PERM = _transpose_descriptor_layout()


@functools.lru_cache(maxsize=256)
def _bin_select_matrix(L: int, n_f: int, step: int, bin_size: int,
                       min_bound: int) -> np.ndarray:
    """(L, n_f·4) 0/1 matrix fusing the VALID box sum AND the keypoint/bin
    gather of one image axis into a single MXU matmul: column (f, b) sums
    pixels [j, j+bin) with j = clip(min_bound + f·step + b·bin − bin//2,
    0, L−bin) — exactly the ``reduce_window`` + double-gather it replaces
    (that pair materialized the full (..., T, Hb, Wb) box tensor and two
    gather intermediates; measured on v5e, the matmul form removes them
    for sub-ms cost)."""
    M = np.zeros((L, n_f * NUM_BIN_S), np.float32)
    for f in range(n_f):
        for b in range(NUM_BIN_S):
            j = min_bound + f * step + b * bin_size - bin_size // 2
            j = min(max(j, 0), L - bin_size)
            M[j : j + bin_size, f * NUM_BIN_S + b] = 1.0
    return M


@functools.partial(
    jax.jit,
    static_argnames=(
        "step", "bin_size", "min_bound", "height", "width", "impl",
        "pallas_tile", "pallas_tier", "pallas_variant",
    ),
)
def _dsift_single_scale(img, step: int, bin_size: int, min_bound: int,
                        height: int, width: int, impl: str = "auto",
                        pallas_tile: int = 0, pallas_tier: str = "f32",
                        pallas_variant: str = "unroll"):
    """One dsift scale over a batch: (..., H, W) -> (..., ny*nx, 128) plus
    the pre-normalization gradient mass (..., ny*nx).

    Three mathematically-identical bin-aggregation forms (fp summation
    order differs; cross-path agreement pinned in ``tests/test_sift.py``
    and ``tests/test_pallas_extraction.py``): selection matmuls on TPU
    (box sum + keypoint/bin gather fused onto the MXU, no (..., T, Hb, Wb)
    box tensor), ``reduce_window`` + gathers elsewhere (the matmul form's
    L/4 extra MACs are a real cost without an MXU — and the jax-CPU anchor
    must time the CPU-best formulation), and the fused Pallas kernel
    (``ops/pallas/extraction.py::sift_oriented_bins`` — binning × column
    matmul in VMEM, so the (..., T, H, W) energy tensor never reaches HBM;
    selected by ``KEYSTONE_PALLAS`` via the eager wrapper).
    ``impl``: "auto" | "matmul" | "window" | "pallas" (forced, for parity
    tests); ``pallas_tile`` is the autotuned row-tile height (0 = the
    kernel default) and ``pallas_tier`` the storage dtype tier
    (``KEYSTONE_PRECISION_TIER``); ``pallas_variant`` the generated
    kernel form (``sift_bins_plan``'s measured winner) — all resolved
    EAGERLY by the caller and jit-static here."""
    mag, angle = _gradient_polar(img)

    ny, nx = dsift_geometry(width, height, step, bin_size, min_bound)
    use_pallas = impl == "pallas"
    use_matmul = impl == "matmul" or (
        impl == "auto" and jax.default_backend() == "tpu"
    )
    if use_pallas or use_matmul:
        # box sum + keypoint/bin gather per axis = one 0/1 selection matmul
        # (see _bin_select_matrix); XLA fuses the energies producer into the
        # first matmul, so the (..., T, Hb, Wb) box tensor never exists
        My = jnp.asarray(
            _bin_select_matrix(height, ny, step, bin_size, min_bound)
        )
        Mx_np = _bin_select_matrix(width, nx, step, bin_size, min_bound)
        if use_pallas:
            from keystone_tpu.ops.pallas.extraction import sift_oriented_bins

            # fused binning × selection: (..., T, H, nx*4) with no
            # (..., T, H, W) energy tensor in HBM
            gx = sift_oriented_bins(
                mag, angle, Mx_np, tile_r=pallas_tile or 256,
                tier=pallas_tier, variant=pallas_variant,
            )
        else:
            energies = _orientation_energies(mag, angle)  # (..., T, H, W)
            # (..., T, H, W) @ (W, nx*4) -> (..., T, H, nx*4)
            gx = jnp.matmul(
                energies, jnp.asarray(Mx_np),
                preferred_element_type=jnp.float32,
            )
        g = jnp.einsum(
            "...hq,hp->...pq", gx, My, preferred_element_type=jnp.float32
        )  # (..., T, ny*4, nx*4)
        g = g.reshape(*g.shape[:-2], ny, NUM_BIN_S, nx, NUM_BIN_S)
    else:
        energies = _orientation_energies(mag, angle)  # (..., T, H, W)
        box = _box_sums(energies, bin_size)  # (..., T, Hb, Wb)
        # frame origin o = min_bound + f·step; spatial bin i is the box of
        # width bin_size centered at o + i·bin, i.e. box index
        # o + i·bin - bin//2
        fy = min_bound + jnp.arange(ny) * step
        fx = min_bound + jnp.arange(nx) * step
        off = jnp.arange(NUM_BIN_S) * bin_size - bin_size // 2
        iy = jnp.clip(fy[:, None] + off[None, :], 0, box.shape[-2] - 1)
        ix = jnp.clip(fx[:, None] + off[None, :], 0, box.shape[-1] - 1)
        g = box[..., :, iy, :][..., :, :, :, ix]  # (..., T, ny, 4, nx, 4)
    # vl element layout is t + T*(x_vl + 4*y_vl); the reference passes images
    # with vl-width = xDim = image height (Image.scala:139), so vl-x bins are
    # our axis-0 (by) bins and vl-y bins our axis-1 (bx) bins: element order
    # (bx, by, t) row-major
    g = jnp.moveaxis(g, -5, -1)  # (..., ny, by, nx, bx, T)
    g = jnp.swapaxes(g, -4, -3)  # (..., ny, nx, by, bx, T)
    g = jnp.swapaxes(g, -3, -2)  # (..., ny, nx, bx, by, T)
    desc = g.reshape(*g.shape[:-5], ny * nx, NUM_BIN_S, NUM_BIN_S, NUM_BIN_T)
    desc = desc.reshape(*desc.shape[:-3], NUM_BIN_S * NUM_BIN_S * NUM_BIN_T)

    mass = jnp.linalg.norm(desc, axis=-1)
    normed = desc / jnp.maximum(mass, 1e-10)[..., None]
    clamped = jnp.minimum(normed, 0.2)
    norm2 = jnp.linalg.norm(clamped, axis=-1)
    final = clamped / jnp.maximum(norm2, 1e-10)[..., None]
    return final, mass


class SIFTExtractor(Transformer):
    """Dense multi-scale SIFT: (H, W) or (H, W, 1) grayscale float image ->
    (num_keypoints, 128) quantized descriptors (float32 holding 0..255 ints,
    like the reference's short-quantized output).

    Params mirror ``SIFTExtractor.scala:16``: step_size=3, bin_size=4,
    scales=4, scale_step=1.
    """

    step_size: int = struct.field(pytree_node=False, default=3)
    bin_size: int = struct.field(pytree_node=False, default=4)
    scales: int = struct.field(pytree_node=False, default=4)
    scale_step: int = struct.field(pytree_node=False, default=1)

    def __contract__(self):
        """Declared contract (``analysis/contracts.py``): rank-3/4 floating
        image batches in; the template's 64² frame admits every default
        scale ladder, and the 128-dim descriptor output is H/W-invariant."""
        from keystone_tpu.analysis import contracts as C

        return C.NodeContract(
            accepts=lambda a: (
                C.expect_rank(a, (3, 4),
                              "grayscale image batch (n, H, W[, C])")
                or C.expect_floating(a, "images")
            ),
            in_template=lambda: C.spec_struct(1, 64, 64),
        )

    def num_descriptors(self, height: int, width: int) -> int:
        total = 0
        for s in range(self.scales):
            ny, nx = dsift_geometry(
                width,
                height,
                self.step_size + s * self.scale_step,
                self.bin_size + 2 * s,
                (1 + 2 * self.scales) - 3 * s,
            )
            total += ny * nx
        return total

    def apply(self, img):
        """Single image: (H, W) or (H, W, C) — only channel 0 is used, like
        the reference's ``getSingleChannelAsFloatArray``."""
        if img.ndim == 3:
            img = img[..., 0]
        return self._extract(img)

    def apply_batch(self, imgs):
        """Batch: (N, H, W) or (N, H, W, C)."""
        if imgs.ndim == 4:
            imgs = imgs[..., 0]
        return self._extract(imgs)

    def _extract(self, img):
        # ONE compiled program for all scales + layout + quantization: run
        # eagerly, the tail ops (concat/perm/quantize over the (N, kp, 128)
        # tensor — GBs at flagship chunks) each pay a full HBM round trip
        # and dispatch; fused they ride the per-scale epilogues (measured
        # ~5x on a 2048-image 64² chunk, v5e).
        # Kernel/twin selection + tile resolution happen HERE, eagerly:
        # the decision and the autotuned tile are jit-static below, so
        # KEYSTONE_PALLAS=0 reproduces the exact prior program.
        impl, tile, tier, variant = _resolve_impl_and_tile(self, img)
        return _extract_jit(
            img, self.step_size, self.bin_size, self.scales,
            self.scale_step, impl, tile, tier, variant,
        )


def _resolve_impl_and_tile(
    node: "SIFTExtractor", img
) -> Tuple[str, int, str, str]:
    """``KEYSTONE_PALLAS`` + autotuner + precision-tier resolution for one
    extract call (``"auto"`` keeps the pre-kernel selection verbatim). The
    tile is resolved at scale-0 geometry — the dominant scale — and shared
    by all scales (buckets are power-of-two anyway); the tier
    (``KEYSTONE_PRECISION_TIER``) is resolved here too, so both ride into
    the jit as static arguments and a knob flip always recompiles instead
    of serving a stale program. Sweeps are suppressed when the image is a
    tracer (extract under an outer jit): lookup/default only. The kernel
    VARIANT rides along the same way: ``sift_bins_plan`` arbitrates the
    measured cross-variant winner (persisted entries only unless
    sweeping), and the name is jit-static like the tile."""
    from keystone_tpu.core.cache import has_tracers
    from keystone_tpu.linalg.solvers import resolve_precision_tier
    from keystone_tpu.ops.pallas.extraction import (
        pallas_enabled,
        sift_bins_plan,
    )

    if not pallas_enabled():
        return "auto", 0, "f32", "unroll"
    tier = resolve_precision_tier(None)
    shape = img.shape
    height, width = shape[-2], shape[-1]
    lead = 1
    for s in shape[:-2]:
        lead *= int(s)
    _, nx = dsift_geometry(
        width, height, node.step_size, node.bin_size, 1 + 2 * node.scales
    )
    variant, tile = sift_bins_plan(
        lead * height, width, max(nx, 1) * NUM_BIN_S,
        allow_sweep=not has_tracers(img), tier=tier,
    )
    return "pallas", int(tile), tier, variant


@functools.partial(
    jax.jit,
    static_argnames=(
        "step_size", "bin_size", "scales", "scale_step", "impl",
        "pallas_tile", "pallas_tier", "pallas_variant",
    ),
)
def _extract_jit(img, step_size: int, bin_size: int, scales: int,
                 scale_step: int, impl: str = "auto", pallas_tile: int = 0,
                 pallas_tier: str = "f32", pallas_variant: str = "unroll"):
    height, width = img.shape[-2], img.shape[-1]
    per_scale = []
    for s in range(scales):
        bin_s = bin_size + 2 * s
        step_s = step_size + s * scale_step
        min_bound = (1 + 2 * scales) - 3 * s
        smoothed = _gaussian_blur(img, bin_s / 6.0)
        desc, mass = _dsift_single_scale(
            smoothed, step_s, bin_s, min_bound, height, width, impl,
            pallas_tile, pallas_tier, pallas_variant,
        )
        desc = jnp.where((mass > CONTRAST_THRESHOLD)[..., None], desc, 0.0)
        per_scale.append(desc)
    descs = jnp.concatenate(per_scale, axis=-2)  # scale-major, (N, 128)
    descs = descs[..., _TRANSPOSE_PERM]
    return jnp.minimum(jnp.floor(512.0 * descs), 255.0)
