"""Fisher Vector encoding of local descriptors against a GMM vocabulary.

Reference: ``nodes/images/external/FisherVector.scala:14-34`` → C++ enceval
``fisher<float>`` with ``alpha=1.0, pnorm=0.0`` (no power/L2 normalization
inside the encoder, ``src/main/cpp/EncEval.cxx:67-70``); output is the
2·D·K gradient block (means then variances).

Math (Perronnin & Dance / Sánchez et al.): with posteriors q_nk over N
descriptors,

    FV_μk = 1/(N·√w_k)   · Σ_n q_nk (x_n − μ_k)/σ_k
    FV_σk = 1/(N·√(2w_k)) · Σ_n q_nk [((x_n − μ_k)/σ_k)² − 1]

i.e. the Fisher-normalized gradient of the mean GMM log-likelihood — which
gives an independent test oracle via ``jax.grad`` (tests verify the encoding
equals the autodiff gradient up to the closed-form Fisher scaling).

Output shape per item: (dims, 2·k) — column j<k is the mean-gradient for
center j, column k+j the variance-gradient — matching the reference's
``numDims×(2·numCentroids)`` (``FisherVector.scala:29-33``).

One item = one (n_desc, dims) descriptor matrix. Posteriors use the shared
centered affine log-density (``_affine_params`` from
``ops/pallas/moments.py``) and the moments are plain MXU matmuls against
the (n_desc, k) posterior matrix — never the (n, k, d) broadcast of the
naive per-descriptor form. Dense and sliced/streaming encodings share one
implementation (:func:`_fv_cols`); the strict no-(n,k)-intermediate Pallas
kernel remains available for the GMM *fit* path in ``ops/pallas/moments.py``.
"""

from __future__ import annotations

from typing import ClassVar

import jax
import jax.numpy as jnp
from flax import struct

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.pallas.moments import _affine_params


class FisherVector(Transformer):
    gmm: GaussianMixtureModel

    def __contract__(self):
        """The acceptance-critical contract: FV encode consumes rank-3
        (n, n_desc, d) descriptor batches whose trailing dim is the GMM's —
        a flattened/mis-ranked producer is a C1 at chain construction."""
        from keystone_tpu.analysis import contracts as C

        d = int(self.gmm.means.shape[1])
        return C.NodeContract(
            accepts=lambda a: (
                C.expect_rank(a, (3,), "descriptor batch (n, n_desc, d)")
                or C.expect_floating(a, "descriptors")
                or C.expect_last_dim(a, d, "the GMM dimension")
            ),
            in_template=lambda: C.spec_struct(1, 8, d),
        )

    def apply(self, descriptors):
        """(n_desc, d) -> (d, 2k). Delegates to :func:`_fv_cols` (the full
        column range) so the dense and sliced/streaming paths share one
        implementation of the gradient formulas and cannot drift; the
        autodiff-oracle test therefore covers both."""
        k, d = self.gmm.means.shape
        flat = _fv_cols(descriptors, self.gmm, 0, 2 * k)  # column-major
        return flat.reshape(2 * k, d).T  # (d, 2k)


# ---------------------------------------------------------------------------
# Streaming (out-of-core) Fisher features: the flagship ImageNet regime.
#
# The standard featurizer chain is FV → vectorize → L2-normalize →
# signed-Hellinger → L2-normalize (``ImageNetSiftLcsFV.scala:29-39``). The
# full feature vector (d·2k per branch; 32 768 at PCA-64 / vocab 256) never
# needs to exist to compute a column block of it:
#
# 1. MatrixVectorizer flattens the (d, 2k) FV column-major (the Breeze
#    convention), so the final feature order is center-major — column j < k
#    is the d-dim mean-gradient of center j, column k+j the variance
#    gradient. A contiguous feature block = a contiguous run of FV columns,
#    and its moments only involve that run's centers (posteriors still need
#    all k — an (n_desc, k) matmul, cheap next to the solver's grams).
# 2. The two L2 normalizations cancel:
#        out = h / ‖h‖₂,  h = sign(z)·√|z|,  z = v/‖v‖₂
#            = sign(v)·√|v| / √‖v‖₁           (‖h‖₂² = ‖v‖₁/‖v‖₂)
#    so one scalar per image — the raw FV's L1 norm — fully determines
#    every block of the normalized output.
#
# ``fisher_l1_norms`` computes those scalars in one chunked pre-pass;
# ``FisherVectorSliceNormalized`` then emits any column run of the final
# features — exactly the block interface
# ``BlockWeightedLeastSquaresEstimator.fit_streaming`` wants.
# ---------------------------------------------------------------------------


def _fv_posteriors(descriptors, gmm: GaussianMixtureModel):
    """Full-k posteriors (n_desc, k), their sums, and the centered
    descriptors + center (the shared prefix of every column block)."""
    x = jnp.asarray(descriptors, jnp.float32)
    center = jnp.mean(x, axis=0)
    xc = x - center[None]
    A, B, c = _affine_params(
        gmm.means - center[None], gmm.variances, gmm.weights
    )
    ll = xc @ A + (xc * xc) @ B + c[None]
    q = jax.nn.softmax(ll, axis=1)
    return q, jnp.sum(q, axis=0), xc, center


def _fv_cols(descriptors, gmm: GaussianMixtureModel, lo: int, hi: int):
    """Columns [lo, hi) of one descriptor matrix's (d, 2k) FV, flattened
    column-major — i.e. the contiguous slice [lo·d, hi·d) of the full
    vectorized FV. Moment work scales with (hi-lo); ``lo``/``hi`` are
    static."""
    n = descriptors.shape[0]
    k = gmm.means.shape[0]
    q, qsum_full, xc, center = _fv_posteriors(descriptors, gmm)
    cs = center[None]
    parts = []
    if lo < k:  # mean-gradient columns (centers [lo, min(hi,k)))
        a, b = lo, min(hi, k)
        qs, qsum = q[:, a:b], qsum_full[a:b][:, None]
        qx = qs.T @ xc + qsum * cs  # uncentered (shift identity)
        mu, w = gmm.means[a:b], gmm.weights[a:b]
        grad = (qx - qsum * mu) / jnp.sqrt(gmm.variances[a:b])
        parts.append((grad / (n * jnp.sqrt(w)[:, None])).reshape(-1))
    if hi > k:  # variance-gradient columns (centers [max(lo,k)-k, hi-k))
        a, b = max(lo, k) - k, hi - k
        qs, qsum = q[:, a:b], qsum_full[a:b][:, None]
        qx_c = qs.T @ xc
        qx = qx_c + qsum * cs
        qx2 = qs.T @ (xc * xc) + 2.0 * cs * qx_c + qsum * cs**2
        mu, var, w = gmm.means[a:b], gmm.variances[a:b], gmm.weights[a:b]
        grad = (qx2 - 2.0 * mu * qx + qsum * mu**2) / var - qsum
        parts.append((grad / (n * jnp.sqrt(2.0 * w)[:, None])).reshape(-1))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _fv_moment_impl() -> str:
    """Moment-path implementation: ``"pallas"`` when the Pallas extraction
    family is engaged, else ``"mxu"`` on TPU, ``"f32"`` elsewhere.

    The pallas form (``ops/pallas/extraction.py::fv_moments``) fuses the
    posterior softmax with the moment accumulation per descriptor tile in
    VMEM, so the (n_img, n_desc, k) posterior tensor never reaches HBM —
    the enceval-C++ fusion the XLA twins cannot express. The mxu form packs
    the posterior's two gemms into ONE ``[x | x²] @ [A; B]`` contraction
    (K = 2d instead of two half-empty K = d passes) and runs the moment
    einsums on bf16 inputs with f32 accumulation — measured 22% per-group-
    pass at the flagship shape (v5e, chain protocol), within bf16 rounding
    of the f32 path. The f32 form stays the default off-TPU so the jax-CPU
    anchor times the CPU-best formulation and the autodiff-oracle tests
    keep their exact path (the ``_conv1d_same`` precedent).
    ``KEYSTONE_FV_IMPL=pallas|mxu|f32`` forces a path for cross-path
    parity tests and beats the ``KEYSTONE_PALLAS`` selection."""
    from keystone_tpu.ops.pallas.extraction import pallas_enabled
    from keystone_tpu.utils import knobs

    forced = knobs.get("KEYSTONE_FV_IMPL")
    if forced in ("pallas", "mxu", "f32"):
        return forced
    if pallas_enabled():
        return "pallas"
    return "mxu" if jax.default_backend() == "tpu" else "f32"


def _fv_cols_batch_mxu(x, gmm: GaussianMixtureModel, lo: int, hi: int):
    """MXU-shaped :func:`_fv_cols_batch` (see :func:`_fv_moment_impl`).

    Structure: one (n·n_desc, 2d) @ (2d, k) posterior gemm over the
    concatenated ``[x | x²]`` in bf16 (f32 accumulation), f32 softmax,
    then bf16 moment einsums against the same ``[x | x²]`` — the variance
    range's qx and qx2 ride ONE einsum with N = 2d (full lane tiles), and
    a full-range call (``fisher_l1_norms``; any group whose mean and
    variance ranges coincide) gets both moments for all its centers from
    that single einsum."""
    n_img, nd, d = x.shape
    k = gmm.means.shape[0]
    if n_img == 0:
        return jnp.zeros((0, (hi - lo) * d), jnp.float32)
    f32 = jnp.float32
    A, B, c0 = _affine_params(gmm.means, gmm.variances, gmm.weights)
    AB = jnp.concatenate([A, B], axis=0).astype(jnp.bfloat16)  # (2d, k)
    xb = jnp.asarray(x, jnp.bfloat16)
    x2 = jnp.concatenate([xb, xb * xb], axis=2)  # (n, nd, 2d)
    ll = jnp.matmul(
        x2.reshape(-1, 2 * d), AB, preferred_element_type=f32
    ) + c0[None]
    q = jax.nn.softmax(ll.reshape(n_img, nd, k), axis=2)
    qsum_full = q.sum(axis=1)  # (n, k) f32
    inv_n = 1.0 / nd
    m_rng = (lo, min(hi, k)) if lo < k else None
    v_rng = (max(lo, k) - k, hi - k) if hi > k else None

    def moments(a, b, want_x2):
        qb = q[:, :, a:b].astype(jnp.bfloat16)
        rhs = x2 if want_x2 else xb
        return jnp.einsum(
            "nik,nij->nkj", qb, rhs, preferred_element_type=f32
        )

    if m_rng is not None and m_rng == v_rng:
        qm = moments(*m_rng, True)
        qx_m = qx_v = qm[..., :d]
        qx2_v = qm[..., d:]
    else:
        qx_m = moments(*m_rng, False) if m_rng is not None else None
        if v_rng is not None:
            qm = moments(*v_rng, True)
            qx_v, qx2_v = qm[..., :d], qm[..., d:]
    parts = []
    if m_rng is not None:
        a, b = m_rng
        qsum = qsum_full[:, a:b, None]
        mu, w = gmm.means[a:b], gmm.weights[a:b]
        grad = (qx_m - qsum * mu[None]) / jnp.sqrt(gmm.variances[a:b])[None]
        parts.append(
            (grad * (inv_n / jnp.sqrt(w))[None, :, None]).reshape(n_img, -1)
        )
    if v_rng is not None:
        a, b = v_rng
        qsum = qsum_full[:, a:b, None]
        mu, var, w = gmm.means[a:b], gmm.variances[a:b], gmm.weights[a:b]
        grad = (
            qx2_v - 2.0 * mu[None] * qx_v + qsum * (mu**2)[None]
        ) / var[None] - qsum
        parts.append(
            (grad * (inv_n / jnp.sqrt(2.0 * w))[None, :, None]).reshape(n_img, -1)
        )
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _fv_cols_batch_pallas(x, gmm: GaussianMixtureModel, lo: int, hi: int):
    """Pallas-kernel :func:`_fv_cols_batch` (see :func:`_fv_moment_impl`).

    One fused kernel pass (``ops/pallas/extraction.py::fv_moments``)
    produces every image's uncentered ``(qsum, qx, qx2)`` without an HBM
    posterior tensor; the gradient formulas below are the same arithmetic
    as the f32 twin on the same uncentered moments, so the two paths agree
    to f32 rounding (pinned in ``tests/test_pallas_extraction.py``). The
    kernel always accumulates full-k moments — they ride the posterior
    matmuls already in VMEM, so a narrow [lo, hi) block costs the same
    kernel pass as a full-range call.

    Under ``KEYSTONE_PRECISION_TIER=bf16`` the kernel streams its
    descriptor tiles in bfloat16 (half the dominant HBM read) and the tier
    joins the tile-cache key; resolution happens where the tile is
    resolved — the same trace-time-read semantics as
    :func:`_fv_moment_impl`'s own knob."""
    from keystone_tpu.linalg.solvers import resolve_precision_tier
    from keystone_tpu.ops.pallas.extraction import fv_encode_plan, fv_moments

    n_img, nd, d = x.shape
    k = gmm.means.shape[0]
    if n_img == 0:
        return jnp.zeros((0, (hi - lo) * d), jnp.float32)
    from keystone_tpu.core.cache import has_tracers

    tier = resolve_precision_tier(None)
    variant, tile_nd = fv_encode_plan(
        nd, d, k, allow_sweep=not has_tracers(x), tier=tier
    )
    qsum_full, qx_full, qx2_full = fv_moments(
        x, gmm.means, gmm.variances, gmm.weights, tile_nd=tile_nd,
        tier=tier, variant=variant,
    )
    inv_n = 1.0 / nd
    m_rng = (lo, min(hi, k)) if lo < k else None
    v_rng = (max(lo, k) - k, hi - k) if hi > k else None
    parts = []
    if m_rng is not None:
        a, b = m_rng
        qx = qx_full[:, a:b]
        qsum = qsum_full[:, a:b, None]
        mu, w = gmm.means[a:b], gmm.weights[a:b]
        grad = (qx - qsum * mu[None]) / jnp.sqrt(gmm.variances[a:b])[None]
        parts.append(
            (grad * (inv_n / jnp.sqrt(w))[None, :, None]).reshape(n_img, -1)
        )
    if v_rng is not None:
        a, b = v_rng
        qx = qx_full[:, a:b]
        qx2 = qx2_full[:, a:b]
        qsum = qsum_full[:, a:b, None]
        mu, var, w = gmm.means[a:b], gmm.variances[a:b], gmm.weights[a:b]
        grad = (qx2 - 2.0 * mu[None] * qx + qsum * (mu**2)[None]) / var[None] - qsum
        parts.append(
            (grad * (inv_n / jnp.sqrt(2.0 * w))[None, :, None]).reshape(n_img, -1)
        )
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _fv_cols_batch(x, gmm: GaussianMixtureModel, lo: int, hi: int):
    """Batched :func:`_fv_cols`: columns [lo, hi) of every image's FV,
    shape (n, (hi-lo)·d).

    Same math, different schedule: the posteriors of ALL images' descriptors
    come from ONE flat (n·n_desc, d) @ (d, k) MXU gemm against the global
    affine log-density params, instead of vmap's n small per-image gemms
    with per-image centered params (measured ~2× posterior cost at the
    flagship shapes). The center shift the per-image path uses for
    cancellation headroom is unnecessary here: descriptors reaching FV are
    PCA projections with O(1) magnitudes, so the affine expansion is
    f32-stable uncentered; ``tests/test_pca_gmm_fv.py`` pins batch≡per-image
    agreement. On TPU the MXU-shaped bf16 form is used instead, and under
    ``KEYSTONE_PALLAS`` the fused Pallas kernel
    (:func:`_fv_cols_batch_pallas` / :func:`_fv_cols_batch_mxu` via
    :func:`_fv_moment_impl`)."""
    impl = _fv_moment_impl()
    if impl == "pallas":
        return _fv_cols_batch_pallas(x, gmm, lo, hi)
    if impl == "mxu":
        return _fv_cols_batch_mxu(x, gmm, lo, hi)
    return _fv_cols_batch_f32(x, gmm, lo, hi)


def _fv_cols_batch_f32(x, gmm: GaussianMixtureModel, lo: int, hi: int):
    """The exact-f32 form of :func:`_fv_cols_batch` (its original body) —
    directly addressable so parity tests and the bench's kernel/twin rows
    name their reference without touching the env."""
    n_img, nd, d = x.shape
    k = gmm.means.shape[0]
    if n_img == 0:
        # zero-row buckets (ladder alignment): the -1 reshapes below cannot
        # infer a dimension from a size-0 array
        return jnp.zeros((0, (hi - lo) * d), jnp.float32)
    x = jnp.asarray(x, jnp.float32)
    A, B, c0 = _affine_params(gmm.means, gmm.variances, gmm.weights)
    flat = x.reshape(-1, d)
    ll = flat @ A + (flat * flat) @ B + c0[None]
    q = jax.nn.softmax(ll.reshape(n_img, nd, k), axis=2)
    qsum_full = q.sum(axis=1)  # (n, k)
    inv_n = 1.0 / nd
    # Center ranges: mean-gradient cols need centers [lo, min(hi,k)),
    # variance cols [max(lo,k)-k, hi-k). They overlap for any full-range
    # call (fisher_l1_norms), where ONE first-moment einsum over the union
    # is cheapest — it is the dominant moment FLOPs. For a group straddling
    # the mean/variance boundary with lo > 0 the union would also cover
    # centers [0, lo) whose moments are discarded, so disjoint ranges get
    # separate einsums instead (ADVICE r2).
    m_rng = (lo, min(hi, k)) if lo < k else None
    v_rng = (max(lo, k) - k, hi - k) if hi > k else None
    ranges = [r for r in (m_rng, v_rng) if r is not None]
    overlap = len(ranges) < 2 or (
        max(m_rng[0], v_rng[0]) < min(m_rng[1], v_rng[1])
    )
    if overlap:
        u_lo, u_hi = min(r[0] for r in ranges), max(r[1] for r in ranges)
        qx_u = jnp.einsum("nik,nij->nkj", q[:, :, u_lo:u_hi], x)
        qx_of = lambda a, b: qx_u[:, a - u_lo : b - u_lo]
    else:
        qx_of = lambda a, b: jnp.einsum("nik,nij->nkj", q[:, :, a:b], x)
    parts = []
    if m_rng is not None:
        a, b = m_rng
        qx = qx_of(a, b)
        qsum = qsum_full[:, a:b, None]
        mu, w = gmm.means[a:b], gmm.weights[a:b]
        grad = (qx - qsum * mu[None]) / jnp.sqrt(gmm.variances[a:b])[None]
        parts.append(
            (grad * (inv_n / jnp.sqrt(w))[None, :, None]).reshape(n_img, -1)
        )
    if v_rng is not None:
        a, b = v_rng
        qx = qx_of(a, b)
        qsum = qsum_full[:, a:b, None]
        qx2 = jnp.einsum("nik,nij->nkj", q[:, :, a:b], x * x)
        mu, var, w = gmm.means[a:b], gmm.variances[a:b], gmm.weights[a:b]
        grad = (qx2 - 2.0 * mu[None] * qx + qsum * (mu**2)[None]) / var[None] - qsum
        parts.append(
            (grad * (inv_n / jnp.sqrt(2.0 * w))[None, :, None]).reshape(n_img, -1)
        )
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _row_chunked_map(fn, arrays, chunk: int):
    """Apply a batch function over a pytree of arrays (shared leading axis n)
    in row chunks read in place via ``dynamic_slice`` — unlike a pad/reshape
    chunker, the (multi-GB, resident) inputs are never copied, only sliced.
    ``chunk <= 0`` or ``n <= chunk`` runs one shot; a ragged tail is one
    extra call. The single chunking implementation under both the
    normalized-FV block nodes and :func:`fisher_l1_norms`."""
    n = jax.tree_util.tree_leaves(arrays)[0].shape[0]
    if chunk <= 0 or n <= chunk:
        return fn(arrays)
    num_full = n // chunk

    def step(i):
        sl = jax.tree.map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, i * chunk, chunk, 0),
            arrays,
        )
        return fn(sl)

    out = jax.lax.map(step, jnp.arange(num_full))
    out = jax.tree.map(
        lambda o: o.reshape(num_full * chunk, *o.shape[2:]), out
    )
    if n % chunk:
        tail = fn(jax.tree.map(lambda a: a[num_full * chunk :], arrays))
        out = jax.tree.map(lambda o, t: jnp.concatenate([o, t]), out, tail)
    return out


def fisher_l1_norms(
    descriptors: jax.Array, gmm: GaussianMixtureModel, chunk: int = 512
) -> jax.Array:
    """Per-image L1 norm of the raw vectorized FV, computed in row chunks so
    no more than ``chunk`` full FVs (and their (chunk, n_desc, k) posterior
    intermediates) are ever live (:func:`_row_chunked_map`; ``chunk <= 0`` =
    one shot). Returns (n,), clamped away from zero (the NormalizeRows eps
    guard, ``Stats.scala:112-124``)."""
    k = gmm.means.shape[0]

    l1 = _row_chunked_map(
        lambda D: jnp.sum(jnp.abs(_fv_cols_batch(D, gmm, 0, 2 * k)), axis=1),
        descriptors,
        chunk,
    )
    return jnp.maximum(l1, 2.2e-16)


class FisherVectorSliceNormalized(Transformer):
    """One feature block of the normalized Fisher featurizer chain.

    ``apply_batch`` takes the ``fit_streaming`` raw pytree (a dict) and
    reads ``raw[key]`` = (n, n_desc, d) PCA-reduced descriptors and
    ``raw[l1_key]`` = (n,) L1 norms from :func:`fisher_l1_norms`; emits the
    (n, (col_hi-col_lo)·d) block of sign(v)·√|v|/√‖v‖₁ — the exact
    [col_lo·d, col_hi·d) slice of the reference's FV → vectorize → L2 →
    Hellinger → L2 output (``ImageNetSiftLcsFV.scala:29-39``; see module
    comment for the norm-cancellation identity)."""

    gmm: GaussianMixtureModel
    col_lo: int = struct.field(pytree_node=False, default=0)
    col_hi: int = struct.field(pytree_node=False, default=0)
    key: str = struct.field(pytree_node=False, default="descs")
    l1_key: str = struct.field(pytree_node=False, default="l1")
    # Rows per internal chunk (0 = all at once). Bounds the (rows, n_desc, k)
    # posterior intermediate; chunks are read in place via dynamic_slice —
    # unlike a generic pad/reshape chunker (ChunkedMap), the multi-GB
    # descriptor tensor is never copied.
    row_chunk: int = struct.field(pytree_node=False, default=0)
    # Cache-group column range [group_lo, group_hi) ⊇ [col_lo, col_hi).
    # The per-block FV cost is posterior-dominated and the posteriors are
    # column-independent (measured: a 512-column FV costs the same as a
    # 64-column one), so recomputing them per block wastes a factor of
    # (#blocks in group). A streaming consumer (fit_streaming /
    # streaming_apply_and_evaluate) that sees ``cache_group`` computes
    # ``group_node()`` once and serves each block via ``slice_cached``.
    # group_hi == 0 disables grouping.
    group_lo: int = struct.field(pytree_node=False, default=0)
    group_hi: int = struct.field(pytree_node=False, default=0)
    # Output dtype of apply_batch ("float32" default). A group node emitting
    # its multi-GB (n, group_width) buffer casts each row chunk inside the
    # chunk loop, so no full-width f32 intermediate ever exists.
    out_dtype: str = struct.field(pytree_node=False, default="float32")
    # grouped_block_getter's push-down protocol: group_node(out_dtype=...)
    # is accepted and the group buffer is emitted directly in that dtype
    group_node_supports_out_dtype: ClassVar[bool] = True

    @property
    def cache_group(self):
        """Hashable group id, or None when grouping is disabled / pointless."""
        if self.group_hi <= self.group_lo or (
            self.col_lo == self.group_lo and self.col_hi == self.group_hi
        ):
            return None
        return (self.key, self.l1_key, self.group_lo, self.group_hi)

    def group_node(self, out_dtype=None) -> "FisherVectorSliceNormalized":
        """The node computing the whole group's columns in one pass."""
        return self.replace(
            col_lo=self.group_lo, col_hi=self.group_hi, group_lo=0, group_hi=0,
            out_dtype=str(jnp.dtype(out_dtype)) if out_dtype is not None
            else self.out_dtype,
        )

    def slice_cached(self, group_out):
        """This block's features out of ``group_node()``'s output."""
        d = self.gmm.means.shape[1]
        lo = (self.col_lo - self.group_lo) * d
        hi = (self.col_hi - self.group_lo) * d
        return group_out[:, lo:hi]

    def _fv_batch(self, descs, l1):
        fv = _fv_cols_batch(descs, self.gmm, self.col_lo, self.col_hi)
        out = jnp.sign(fv) * jnp.sqrt(jnp.abs(fv) / l1[:, None])
        return out.astype(jnp.dtype(self.out_dtype))

    def apply_batch(self, raw):
        return _row_chunked_map(
            lambda dl: self._fv_batch(*dl),
            (raw[self.key], raw[self.l1_key]),
            self.row_chunk,
        )

    def apply(self, raw_one):
        return self.apply_batch(jax.tree.map(lambda a: a[None], raw_one))[0]


def make_fisher_block_nodes(
    gmm: GaussianMixtureModel,
    block_size: int,
    key: str = "descs",
    l1_key: str = "l1",
    row_chunk: int = 0,
    cache_blocks: int = 0,
) -> list:
    """Split one branch's d·2k normalized Fisher features into
    ``block_size``-wide :class:`FisherVectorSliceNormalized` nodes
    (``block_size`` must be a multiple of the descriptor dim d).

    ``cache_blocks > 0`` tags runs of that many consecutive blocks as one
    cache group (see the ``group_lo`` field comment): a group-aware streaming
    consumer computes the shared posteriors once per group instead of once
    per block, at the cost of holding the group's (n, cache_blocks·block_size)
    features resident while its blocks are consumed."""
    k, d = gmm.means.shape
    if block_size % d:
        raise ValueError(f"block_size {block_size} not a multiple of dim {d}")
    cols_per_block = block_size // d
    if (2 * k) % cols_per_block:
        raise ValueError(
            f"2k={2*k} FV columns not divisible by {cols_per_block} per block"
        )
    total_cols = 2 * k
    group_cols = max(0, cache_blocks) * cols_per_block
    nodes = []
    for lo in range(0, total_cols, cols_per_block):
        if group_cols:
            glo = (lo // group_cols) * group_cols
            ghi = min(glo + group_cols, total_cols)
        else:
            glo = ghi = 0
        nodes.append(
            FisherVectorSliceNormalized(
                gmm=gmm, col_lo=lo, col_hi=lo + cols_per_block, key=key,
                l1_key=l1_key, row_chunk=row_chunk, group_lo=glo, group_hi=ghi,
            )
        )
    return nodes


class BucketConcatNode:
    """Row-concatenate one feature block across size buckets.

    Variable-size ingest gives each (H, W) bucket its own resident
    descriptor tensor (different per-image descriptor counts — static
    shapes per bucket); the streaming solver wants ONE (n_total, block)
    feature block per column range. This wrapper holds the same column
    range's :class:`FisherVectorSliceNormalized` node for every bucket
    (distinct ``key``/``l1_key`` per bucket) and concatenates their rows —
    making bucketed raw data a drop-in ``fit_streaming`` input. The cache-
    group protocol forwards: the group featurization concatenates per-bucket
    group outputs, and a block's slice is a pure column slice, which
    commutes with row concatenation.
    """

    group_node_supports_out_dtype = True

    def __init__(self, nodes):
        self.nodes = tuple(nodes)

    def apply_batch(self, raw):
        outs = [n.apply_batch(raw) for n in self.nodes]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    @property
    def cache_group(self):
        groups = tuple(n.cache_group for n in self.nodes)
        if any(g is None for g in groups):
            return None
        return groups

    def group_node(self, out_dtype=None):
        return BucketConcatNode(
            [n.group_node(out_dtype=out_dtype) for n in self.nodes]
        )

    def slice_cached(self, group_out):
        # same column range in every bucket: one column slice of the
        # row-concatenated group output
        return self.nodes[0].slice_cached(group_out)


def make_bucketed_fisher_block_nodes(
    gmm: GaussianMixtureModel,
    block_size: int,
    bucket_keys,
    row_chunk: int = 0,
    cache_blocks: int = 0,
) -> list:
    """:func:`make_fisher_block_nodes` across size buckets: one
    :class:`BucketConcatNode` per column block, wrapping that block's node
    for every bucket. ``bucket_keys``: list of ``(key, l1_key)`` raw-pytree
    names, one per bucket, in the row order the labels use."""
    per_bucket = [
        make_fisher_block_nodes(
            gmm, block_size, key=key, l1_key=l1_key,
            row_chunk=row_chunk, cache_blocks=cache_blocks,
        )
        for key, l1_key in bucket_keys
    ]
    return [BucketConcatNode(nodes) for nodes in zip(*per_bucket)]
