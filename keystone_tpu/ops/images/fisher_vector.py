"""Fisher Vector encoding of local descriptors against a GMM vocabulary.

Reference: ``nodes/images/external/FisherVector.scala:14-34`` → C++ enceval
``fisher<float>`` with ``alpha=1.0, pnorm=0.0`` (no power/L2 normalization
inside the encoder, ``src/main/cpp/EncEval.cxx:67-70``); output is the
2·D·K gradient block (means then variances).

Math (Perronnin & Dance / Sánchez et al.): with posteriors q_nk over N
descriptors,

    FV_μk = 1/(N·√w_k)   · Σ_n q_nk (x_n − μ_k)/σ_k
    FV_σk = 1/(N·√(2w_k)) · Σ_n q_nk [((x_n − μ_k)/σ_k)² − 1]

i.e. the Fisher-normalized gradient of the mean GMM log-likelihood — which
gives an independent test oracle via ``jax.grad`` (tests verify the encoding
equals the autodiff gradient up to the closed-form Fisher scaling).

Output shape per item: (dims, 2·k) — column j<k is the mean-gradient for
center j, column k+j the variance-gradient — matching the reference's
``numDims×(2·numCentroids)`` (``FisherVector.scala:29-33``).

One item = one (n_desc, dims) descriptor matrix; the whole encoding rides
the shared GMM-moments path (``ops/pallas/moments.py``) — posteriors and
weighted moments in one MXU-shaped pass, without the (n, k, d) broadcast of
the naive per-descriptor form.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.pallas.moments import gmm_moments_auto


class FisherVector(Transformer):
    gmm: GaussianMixtureModel

    def apply(self, descriptors):
        """(n_desc, d) -> (d, 2k)."""
        gmm = self.gmm
        n = descriptors.shape[0]
        sigma = jnp.sqrt(gmm.variances)  # (k, d)

        qsum, qx, qx2 = gmm_moments_auto(
            descriptors, gmm.means, gmm.variances, gmm.weights
        )

        # Σ q (x-μ)/σ = (qx - qsum·μ)/σ
        grad_mu = (qx - qsum[:, None] * gmm.means) / sigma
        # Σ q [((x-μ)/σ)² - 1] = (qx2 - 2μ·qx + qsum·μ²)/σ² - qsum
        grad_sig = (
            qx2 - 2.0 * gmm.means * qx + qsum[:, None] * gmm.means**2
        ) / gmm.variances - qsum[:, None]

        fv_mu = grad_mu / (n * jnp.sqrt(gmm.weights)[:, None])
        fv_sig = grad_sig / (n * jnp.sqrt(2.0 * gmm.weights)[:, None])
        return jnp.concatenate([fv_mu.T, fv_sig.T], axis=1)  # (d, 2k)
