"""Small per-pixel image nodes.

Image convention throughout this framework: an image is an ``(H, W, C)``
float32 array (channel fastest in memory), so the reference's channel-major
vector layout (``utils/images/Image.scala:179``: index ``c + x*C + y*C*X``)
is exactly ``img.reshape(-1)`` — no layout zoo needed; XLA owns physical
layout on TPU. The reference's five ``Image`` implementations collapse to
this one array type, and ``ImageMetadata`` is just ``.shape``.
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer


class GrayScaler(Transformer):
    """NTSC grayscale; keeps a single channel.

    Reference: ``utils/images/ImageUtils.scala:55-87`` + ``nodes/images/
    GrayScaler.scala:9``. The reference hardcodes BGR channel order (its JPEG
    decode path); this repo's canonical layout is RGB (see loaders/cifar.py),
    so the default is ``"rgb"`` — pass ``channel_order="bgr"`` for data that
    arrives BGR. Non-3-channel images use sqrt of the mean square.
    """

    channel_order: str = struct.field(pytree_node=False, default="rgb")

    def __contract__(self):
        from keystone_tpu.analysis import contracts as C

        return C.NodeContract(
            accepts=lambda a: (
                C.expect_rank(a, (4,), "image batch (n, H, W, C)")
                or C.expect_floating(a, "images")
            ),
            in_template=lambda: C.spec_struct(1, 64, 64, 3),
        )

    def apply(self, img):
        from keystone_tpu.ops.images.image_utils import to_grayscale

        return to_grayscale(img, self.channel_order)


class PixelScaler(Transformer):
    """Byte pixels -> [0,1]. Reference: ``nodes/images/PixelScaler.scala:10-13``."""

    def apply(self, img):
        return img / 255.0


class ImageVectorizer(Transformer):
    """Image -> channel-major vector (``nodes/images/ImageVectorizer.scala:11-14``);
    with the (H, W, C) convention this is a plain flatten."""

    def apply(self, img):
        return img.reshape(-1)


class ImageExtractor(Transformer):
    """``LabeledData`` -> images. Reference:
    ``nodes/images/LabeledImageExtractors.scala:16``."""

    def apply(self, item):
        return item.data

    def apply_batch(self, xs):
        return xs.data


class MultiLabeledImageExtractor(ImageExtractor):
    """Reference: ``nodes/images/LabeledImageExtractors.scala:30``."""


class LabelExtractor(Transformer):
    """``LabeledData`` -> int labels. Reference:
    ``nodes/images/LabeledImageExtractors.scala:9``."""

    def apply(self, item):
        return item.labels

    def apply_batch(self, xs):
        return xs.labels


class MultiLabelExtractor(LabelExtractor):
    """``LabeledData`` -> multi-hot label rows. Reference:
    ``nodes/images/LabeledImageExtractors.scala:23``."""


class SymmetricRectifier(Transformer):
    """Doubles channels: ``max(maxVal, x-α)`` ++ ``max(maxVal, -x-α)``.

    Reference: ``nodes/images/SymmetricRectifier.scala:6-31``.
    """

    max_val: float = struct.field(pytree_node=False, default=0.0)
    alpha: float = struct.field(pytree_node=False, default=0.0)

    def apply(self, img):
        return jnp.concatenate(
            [
                jnp.maximum(self.max_val, img - self.alpha),
                jnp.maximum(self.max_val, -img - self.alpha),
            ],
            axis=-1,
        )
