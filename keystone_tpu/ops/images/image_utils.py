"""Functional image utilities — the ``utils/images/ImageUtils.scala`` layer.

The reference's ``Image`` trait + five array-layout implementations
(``utils/images/Image.scala:19-263``) existed to avoid copies between
Spark's JVM byte buffers and Breeze; with ``jax.Array`` there is ONE
canonical layout — ``(H, W, C)`` float32, channel-last so the channel axis
is the XLA minor (lane) dimension — and the layout zoo collapses to plain
array ops. ``ImageConversions`` (BufferedImage decode, grayscale
triplication, ``ImageConversions.scala:10-37``) lives in the native ingest
(``native/ingest.py:decode_jpeg``). What remains here are the functional
helpers the reference exposes on ``ImageUtils``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def conv2d_same(img, x_filter: np.ndarray, y_filter: np.ndarray):
    """The reference's ``ImageUtils.conv2D`` contract (``ImageUtils.scala:
    162-274``): true separable convolution (filter flipped), zero padding
    floor((k-1)/2) low / ceil((k-1)/2) high, output size = input size.
    ``img``: (..., H, W).

    Note: ``x_filter`` here runs along our axis -1 (width). The reference's
    ``xFilter`` runs along ref-x = image height — callers translating
    reference ``conv2D(img, A, B)`` calls should pass ``(B, A)`` here.
    """

    def pass1d(x, filt, axis):
        k = len(filt)
        lo, hi = (k - 1) // 2, k - 1 - (k - 1) // 2
        kernel = jnp.asarray(np.asarray(filt, np.float32)[::-1])
        moved = jnp.moveaxis(x, axis, -1)
        padded = jnp.pad(
            moved, [(0, 0)] * (moved.ndim - 1) + [(lo, hi)], mode="constant"
        )
        flat = padded.reshape(-1, 1, padded.shape[-1])
        res = jax.lax.conv_general_dilated(
            flat, kernel.reshape(1, 1, -1), (1,), "VALID",
            dimension_numbers=("NCH", "OIH", "NCH"),
        )
        return jnp.moveaxis(res.reshape(moved.shape), -1, axis)

    return pass1d(pass1d(img, x_filter, -1), y_filter, -2)


def to_grayscale(img, channel_order: str = "rgb"):
    """NTSC luminance, keeping a singleton channel axis.

    Reference: ``ImageUtils.toGrayScale`` (``ImageUtils.scala:55-87``; BGR
    there — its JPEG path decodes BGR — RGB here, see ``decode_jpeg``).
    """
    if img.shape[-1] == 3:
        w = jnp.array([0.2989, 0.5870, 0.1140], img.dtype)
        if channel_order == "bgr":
            w = w[::-1]
        return (img @ w)[..., None]
    return jnp.sqrt(jnp.mean(img**2, axis=-1, keepdims=True))


def map_pixels(img, fn: Callable):
    """Apply an elementwise function to every pixel value.

    Reference: ``ImageUtils.mapPixels`` (``ImageUtils.scala:97-116``). Under
    jit this is a fused elementwise op, not a Python loop.
    """
    return fn(img)


def pixel_combine(a, b, fn: Callable = jnp.add):
    """Combine two same-shape images pixelwise.

    Reference: ``ImageUtils.pixelCombine`` (``ImageUtils.scala:127-151``).
    """
    return fn(a, b)


def split_channels(img) -> Tuple[jax.Array, ...]:
    """Split (H, W, C) into C single-channel (H, W, 1) images.

    Reference: ``ImageUtils.splitChannels`` (``ImageUtils.scala:282-303``).
    """
    return tuple(
        img[..., c : c + 1] for c in range(img.shape[-1])
    )
