"""Functional image utilities — the ``utils/images/ImageUtils.scala`` layer.

The reference's ``Image`` trait + five array-layout implementations
(``utils/images/Image.scala:19-263``) existed to avoid copies between
Spark's JVM byte buffers and Breeze; with ``jax.Array`` there is ONE
canonical layout — ``(H, W, C)`` float32, channel-last so the channel axis
is the XLA minor (lane) dimension — and the layout zoo collapses to plain
array ops. ``ImageConversions`` (BufferedImage decode, grayscale
triplication, ``ImageConversions.scala:10-37``) lives in the native ingest
(``native/ingest.py:decode_jpeg``). What remains here are the functional
helpers the reference exposes on ``ImageUtils``.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# Max axis length that routes a separable 1-D convolution through the
# banded-matrix matmul (below) instead of lax.conv. A rank-1 single-channel
# conv cannot use the MXU at all — at extractor batch shapes it runs as
# hundreds of thousands of tiny VPU convolutions (measured: the LCS box
# filters alone were ~0.125 s per 2048-image 64² chunk, the top extraction
# cost at the flagship). An (L, L) banded matmul pays L/k more MACs but
# rides the MXU; up to a few hundred pixels that trade is won outright.
_MATMUL_CONV_MAX_LEN = 512


@functools.lru_cache(maxsize=64)
def _conv_band_matrix(filt_bytes: bytes, k: int, L: int, mode: str) -> np.ndarray:
    """(L, L) matrix K with ``out = x @ K`` ≡ the 1-D "same" convolution of
    x (length L) with the length-k filter — true convolution (flipped
    filter), pad floor((k-1)/2) low / ceil high. ``mode``: "zero" pads with
    zeros (the ImageUtils.conv2D contract); "edge" folds out-of-range taps
    onto the boundary pixel (vl_imsmooth's replicate padding)."""
    filt = np.frombuffer(filt_bytes, np.float32)
    lo = (k - 1) // 2
    flipped = filt[::-1]
    K = np.zeros((L, L), np.float32)
    for j in range(L):
        for m in range(k):
            src = j + m - lo
            if mode == "edge":
                src = min(max(src, 0), L - 1)
            elif not (0 <= src < L):
                continue
            K[src, j] += flipped[m]
    return K


def _conv1d_same(x, filt: np.ndarray, axis: int, mode: str = "zero",
                 impl: str = "auto"):
    """1-D "same" convolution along ``axis`` (true convolution, zero or
    edge padding): banded matmul for small axes ON TPU, lax.conv otherwise.

    The matmul form pays L/k more MACs — free on the MXU (a rank-1
    single-channel conv cannot use it at all), a genuine pessimization on
    CPU — so ``auto`` picks by backend at trace time. That also keeps the
    jax-CPU anchor (scripts/cpu_baseline.py) honest: the CPU side times
    the CPU-best formulation, not a TPU-shaped one. ``impl``:
    "auto" | "matmul" | "conv" (forced, for cross-path parity tests).
    """
    # lint: disable=R1 (filt is a static host-side numpy filter; it folds
    # into the band matrix at trace time by design, never a device sync)
    filt = np.ascontiguousarray(np.asarray(filt, np.float32))
    k = len(filt)
    moved = jnp.moveaxis(x, axis, -1)
    L = moved.shape[-1]
    use_matmul = impl == "matmul" or (
        impl == "auto"
        and L <= _MATMUL_CONV_MAX_LEN
        and jax.default_backend() == "tpu"
    )
    if use_matmul:
        K = jnp.asarray(_conv_band_matrix(filt.tobytes(), k, L, mode))
        res = jnp.matmul(moved, K, preferred_element_type=jnp.float32)
        return jnp.moveaxis(res, -1, axis)
    lo, hi = (k - 1) // 2, k - 1 - (k - 1) // 2
    pad_mode = "edge" if mode == "edge" else "constant"
    padded = jnp.pad(
        moved, [(0, 0)] * (moved.ndim - 1) + [(lo, hi)], mode=pad_mode
    )
    kernel = jnp.asarray(filt[::-1])
    flat = padded.reshape(-1, 1, padded.shape[-1])
    res = jax.lax.conv_general_dilated(
        flat, kernel.reshape(1, 1, -1), (1,), "VALID",
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    return jnp.moveaxis(res.reshape(moved.shape), -1, axis)


def conv2d_same(img, x_filter: np.ndarray, y_filter: np.ndarray):
    """The reference's ``ImageUtils.conv2D`` contract (``ImageUtils.scala:
    162-274``): true separable convolution (filter flipped), zero padding
    floor((k-1)/2) low / ceil((k-1)/2) high, output size = input size.
    ``img``: (..., H, W).

    Note: ``x_filter`` here runs along our axis -1 (width). The reference's
    ``xFilter`` runs along ref-x = image height — callers translating
    reference ``conv2D(img, A, B)`` calls should pass ``(B, A)`` here.
    """
    return _conv1d_same(_conv1d_same(img, x_filter, -1), y_filter, -2)


def to_grayscale(img, channel_order: str = "rgb"):
    """NTSC luminance, keeping a singleton channel axis.

    Reference: ``ImageUtils.toGrayScale`` (``ImageUtils.scala:55-87``; BGR
    there — its JPEG path decodes BGR — RGB here, see ``decode_jpeg``).
    """
    if img.shape[-1] == 3:
        w = jnp.array([0.2989, 0.5870, 0.1140], img.dtype)
        if channel_order == "bgr":
            w = w[::-1]
        return (img @ w)[..., None]
    return jnp.sqrt(jnp.mean(img**2, axis=-1, keepdims=True))


def map_pixels(img, fn: Callable):
    """Apply an elementwise function to every pixel value.

    Reference: ``ImageUtils.mapPixels`` (``ImageUtils.scala:97-116``). Under
    jit this is a fused elementwise op, not a Python loop.
    """
    return fn(img)


def pixel_combine(a, b, fn: Callable = jnp.add):
    """Combine two same-shape images pixelwise.

    Reference: ``ImageUtils.pixelCombine`` (``ImageUtils.scala:127-151``).
    """
    return fn(a, b)


def split_channels(img) -> Tuple[jax.Array, ...]:
    """Split (H, W, C) into C single-channel (H, W, 1) images.

    Reference: ``ImageUtils.splitChannels`` (``ImageUtils.scala:282-303``).
    """
    return tuple(
        img[..., c : c + 1] for c in range(img.shape[-1])
    )
