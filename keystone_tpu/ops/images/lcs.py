"""Local Color Statistics (LCS) descriptors.

Reference: ``nodes/images/LCSExtractor.scala:25-130`` — per-channel box-filter
means/stds (via ``ImageUtils.conv2D``), then for each keypoint on a
(stride, stride_start) grid, the means and stds of a 4×4 neighborhood of
sub-patches at offsets ``-2s+s/2-1 .. s+s/2-1`` step ``s`` → 96-dim
descriptors (3 channels × 16 sub-regions × {mean, std}).

Returns (num_keypoints, 96) rows (the reference emits the 96×N transpose).
Keypoint ordering differs from the reference (row-major here, column-major
there) — downstream consumers (PCA/GMM/FisherVector) aggregate over
descriptors so ordering is immaterial.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer


# Shared ImageUtils.conv2D equivalent; re-exported here for back-compat.
from keystone_tpu.ops.images.image_utils import conv2d_same  # noqa: E402


class LCSExtractor(Transformer):
    stride: int = struct.field(pytree_node=False, default=4)
    stride_start: int = struct.field(pytree_node=False, default=16)
    sub_patch_size: int = struct.field(pytree_node=False, default=6)

    def _neighbor_offsets(self) -> np.ndarray:
        s = self.sub_patch_size
        return np.arange(-2 * s + s // 2 - 1, s + s // 2, s)  # e.g. [-10,-4,2,8]

    def apply(self, img):
        """(H, W, C) -> (num_keypoints, C·16·2)."""
        h, w, c = img.shape
        chans = jnp.moveaxis(img, -1, 0)  # (C, H, W)
        box = np.full(self.sub_patch_size, 1.0 / self.sub_patch_size, np.float32)
        means = conv2d_same(chans, box, box)
        sq = conv2d_same(chans * chans, box, box)
        stds = jnp.sqrt(jnp.maximum(sq - means * means, 0.0))

        ys = jnp.arange(self.stride_start, h - self.stride_start, self.stride)
        xs = jnp.arange(self.stride_start, w - self.stride_start, self.stride)
        offs = jnp.asarray(self._neighbor_offsets())

        # sample positions: keypoint grid + neighborhood offsets
        py = (ys[:, None] + offs[None, :]).reshape(-1)  # (ny*4,)
        px = (xs[:, None] + offs[None, :]).reshape(-1)  # (nx*4,)
        m = means[:, py, :][:, :, px]  # (C, ny*4, nx*4)
        s = stds[:, py, :][:, :, px]
        ny, nx, k = ys.shape[0], xs.shape[0], offs.shape[0]
        m = m.reshape(c, ny, k, nx, k)
        s = s.reshape(c, ny, k, nx, k)
        # per keypoint: descriptor ordered (c, ref-x offset, ref-y offset,
        # [mean, std]) — ref-x is our axis 0 (Image.scala:139)
        stacked = jnp.stack([m, s], axis=-1)  # (C, ny, oy, nx, ox, 2)
        stacked = stacked.transpose(1, 3, 0, 2, 4, 5)  # (ny, nx, C, oy, ox, 2)
        return stacked.reshape(ny * nx, c * k * k * 2)

    def num_keypoints(self, h: int, w: int) -> int:
        ny = len(range(self.stride_start, h - self.stride_start, self.stride))
        nx = len(range(self.stride_start, w - self.stride_start, self.stride))
        return ny * nx
