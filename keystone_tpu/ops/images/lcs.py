"""Local Color Statistics (LCS) descriptors.

Reference: ``nodes/images/LCSExtractor.scala:25-130`` — per-channel box-filter
means/stds (via ``ImageUtils.conv2D``), then for each keypoint on a
(stride, stride_start) grid, the means and stds of a 4×4 neighborhood of
sub-patches at offsets ``-2s+s/2-1 .. s+s/2-1`` step ``s`` → 96-dim
descriptors (3 channels × 16 sub-regions × {mean, std}).

Returns (num_keypoints, 96) rows (the reference emits the 96×N transpose).
Keypoint ordering differs from the reference (row-major here, column-major
there) — downstream consumers (PCA/GMM/FisherVector) aggregate over
descriptors so ordering is immaterial.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer


# Shared ImageUtils.conv2D equivalent; re-exported here for back-compat.
from keystone_tpu.ops.images.image_utils import conv2d_same  # noqa: E402


class LCSExtractor(Transformer):
    stride: int = struct.field(pytree_node=False, default=4)
    stride_start: int = struct.field(pytree_node=False, default=16)
    sub_patch_size: int = struct.field(pytree_node=False, default=6)

    def _neighbor_offsets(self) -> np.ndarray:
        s = self.sub_patch_size
        return np.arange(-2 * s + s // 2 - 1, s + s // 2, s)  # e.g. [-10,-4,2,8]

    def __contract__(self):
        from keystone_tpu.analysis import contracts as C

        # a frame that admits at least a few keypoint rows at this stride
        hw = max(64, 2 * self.stride_start + 4 * self.stride)
        return C.NodeContract(
            accepts=lambda a: (
                C.expect_rank(a, (4,), "color image batch (n, H, W, C)")
                or C.expect_floating(a, "images")
            ),
            in_template=lambda: C.spec_struct(1, hw, hw, 3),
        )

    def apply(self, img):
        """(H, W, C) -> (num_keypoints, C·16·2)."""
        return self.apply_batch(img[None])[0]

    def apply_batch(self, imgs):
        """Natively batched (N, H, W, C) path, ONE compiled program — not a
        vmap of per-image programs and not a chain of eager GB-scale ops
        (both measured ~2-4x slower per flagship chunk on v5e)."""
        return _lcs_batch_jit(
            imgs, self.stride, self.stride_start, self.sub_patch_size
        )

    def num_keypoints(self, h: int, w: int) -> int:
        ny = len(range(self.stride_start, h - self.stride_start, self.stride))
        nx = len(range(self.stride_start, w - self.stride_start, self.stride))
        return ny * nx


@functools.partial(
    jax.jit, static_argnames=("stride", "stride_start", "sub_patch_size")
)
def _lcs_batch_jit(imgs, stride: int, stride_start: int, sub_patch_size: int):
    node = LCSExtractor(stride, stride_start, sub_patch_size)
    n, h, w, c = imgs.shape
    chans = jnp.moveaxis(imgs, -1, 1)  # (N, C, H, W)
    box = np.full(sub_patch_size, 1.0 / sub_patch_size, np.float32)
    means = conv2d_same(chans, box, box)
    sq = conv2d_same(chans * chans, box, box)
    stds = jnp.sqrt(jnp.maximum(sq - means * means, 0.0))

    ys = jnp.arange(stride_start, h - stride_start, stride)
    xs = jnp.arange(stride_start, w - stride_start, stride)
    offs = jnp.asarray(node._neighbor_offsets())

    # sample positions: keypoint grid + neighborhood offsets
    py = (ys[:, None] + offs[None, :]).reshape(-1)  # (ny*4,)
    px = (xs[:, None] + offs[None, :]).reshape(-1)  # (nx*4,)
    m = means[:, :, py, :][:, :, :, px]  # (N, C, ny*4, nx*4)
    s = stds[:, :, py, :][:, :, :, px]
    ny, nx, k = ys.shape[0], xs.shape[0], offs.shape[0]
    m = m.reshape(n, c, ny, k, nx, k)
    s = s.reshape(n, c, ny, k, nx, k)
    # per keypoint: descriptor ordered (c, ref-x offset, ref-y offset,
    # [mean, std]) — ref-x is our axis 0 (Image.scala:139)
    stacked = jnp.stack([m, s], axis=-1)  # (N, C, ny, oy, nx, ox, 2)
    stacked = stacked.transpose(0, 2, 4, 1, 3, 5, 6)  # (N, ny, nx, C, ...)
    return stacked.reshape(n, ny * nx, c * k * k * 2)
