"""Filter-bank convolution with optional per-patch normalization/whitening.

Reference: ``nodes/images/Convolver.scala:19-154`` — im2col (``makePatches``)
+ one gemm per image, with optional per-patch mean/variance normalization
(``Stats.normalizeRows`` with ``varConstant``) and whitening-mean subtraction.

TPU design: the im2col+gemm *is* a convolution, so the main compute is one
``lax.conv_general_dilated`` over the whole batch (MXU-tiled by XLA). The
per-patch normalization is decomposed into closed form so no patch matrix is
ever materialized: with patch p, filter f, n = k·k·C,

    normalize(p)·f = (p·f − mean(p)·Σf) / sd(p)

where mean/sd come from two box-filter convolutions (patch sum and patch
sum-of-squares), and the whitener-mean subtraction is a constant per filter:
``(normalize(p) − m)·f = normalize(p)·f − m·f``. Everything fuses.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.learning.zca import ZCAWhitener


class Convolver(Transformer):
    """``filters``: (num_filters, k·k·C), rows in the reference's patch layout
    (y-offset slowest, then x-offset, channel fastest)."""

    filters: jax.Array
    whitener: Optional[ZCAWhitener] = None
    num_channels: int = struct.field(pytree_node=False, default=3)
    normalize_patches: bool = struct.field(pytree_node=False, default=True)
    var_constant: float = struct.field(pytree_node=False, default=10.0)

    @property
    def conv_size(self) -> int:
        k2 = self.filters.shape[1] // self.num_channels
        k = int(round(k2**0.5))
        assert k * k == k2, "filters must be square"
        return k

    def apply(self, img):
        return self.apply_batch(img[None])[0]

    def apply_batch(self, imgs):
        k, c = self.conv_size, self.num_channels
        nf = self.filters.shape[0]
        kernel = self.filters.reshape(nf, k, k, c).transpose(1, 2, 3, 0)  # HWIO
        dn = jax.lax.conv_dimension_numbers(
            imgs.shape, kernel.shape, ("NHWC", "HWIO", "NHWC")
        )
        raw = jax.lax.conv_general_dilated(
            imgs, kernel, (1, 1), "VALID", dimension_numbers=dn
        )  # (N, resH, resW, nF)

        out = raw
        if self.normalize_patches:
            n = k * k * c
            ones = jnp.ones((k, k, c, 1), imgs.dtype)
            s1 = jax.lax.conv_general_dilated(
                imgs, ones, (1, 1), "VALID", dimension_numbers=dn
            )
            s2 = jax.lax.conv_general_dilated(
                imgs * imgs, ones, (1, 1), "VALID", dimension_numbers=dn
            )
            mean = s1 / n
            var = (s2 - s1 * mean) / (n - 1.0)
            sd = jnp.sqrt(var + self.var_constant)
            fsum = jnp.sum(self.filters, axis=1)  # (nF,)
            out = (raw - mean * fsum[None, None, None, :]) / sd
        if self.whitener is not None:
            mf = self.whitener.means @ self.filters.T  # (nF,)
            out = out - mf[None, None, None, :]
        return out
