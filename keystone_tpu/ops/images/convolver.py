"""Filter-bank convolution with optional per-patch normalization/whitening.

Reference: ``nodes/images/Convolver.scala:19-154`` — im2col (``makePatches``)
+ one gemm per image, with optional per-patch mean/variance normalization
(``Stats.normalizeRows`` with ``varConstant``) and whitening-mean subtraction.

TPU design: the im2col+gemm *is* a convolution, so the main compute is one
``lax.conv_general_dilated`` over the whole batch (MXU-tiled by XLA). The
per-patch normalization is decomposed into closed form so no patch matrix is
ever materialized: with patch p, filter f, n = k·k·C,

    normalize(p)·f = (p·f − mean(p)·Σf) / sd(p)

where mean/sd come from two box-filter convolutions (patch sum and patch
sum-of-squares), and the whitener-mean subtraction is a constant per filter:
``(normalize(p) − m)·f = normalize(p)·f − m·f``. Everything fuses.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.learning.zca import ZCAWhitener


class Convolver(Transformer):
    """``filters``: (num_filters, k·k·C), rows in the reference's patch layout
    (y-offset slowest, then x-offset, channel fastest)."""

    filters: jax.Array
    whitener: Optional[ZCAWhitener] = None
    num_channels: int = struct.field(pytree_node=False, default=3)
    normalize_patches: bool = struct.field(pytree_node=False, default=True)
    var_constant: float = struct.field(pytree_node=False, default=10.0)

    @property
    def conv_size(self) -> int:
        k2 = self.filters.shape[1] // self.num_channels
        k = int(round(k2**0.5))
        assert k * k == k2, "filters must be square"
        return k

    def apply(self, img):
        return self.apply_batch(img[None])[0]

    def apply_batch(self, imgs):
        plan = self._pallas_plan(imgs)
        if plan is not None:
            return self._apply_batch_pallas(imgs, *plan)
        return self._apply_batch_xla(imgs)

    def _pallas_plan(self, imgs):
        """``(variant, tile_f, tier)`` when the fused Pallas kernel should
        run, else None (the XLA twin). The kernel is explicit-grade
        (``KEYSTONE_PALLAS=1`` only — see ``ops/pallas/extraction.py``) and
        additionally requires a tile whose per-image working set fits
        VMEM; the loop-order variant is the autotuner's measured
        cross-variant winner (``conv_norm_plan``)."""
        from keystone_tpu.core.cache import has_tracers
        from keystone_tpu.linalg.solvers import resolve_precision_tier
        from keystone_tpu.ops.pallas.extraction import (
            conv_norm_plan,
            pallas_enabled,
        )

        if not pallas_enabled(auto_ok=False):
            return None
        if imgs.dtype != jnp.float32:
            # the kernel computes in f32; other dtypes keep the twin's
            # exact semantics (same gate as the Pallas pooler)
            return None
        k, c = self.conv_size, self.num_channels
        h, w = int(imgs.shape[1]), int(imgs.shape[2])
        if h < k or w < k:
            return None
        tier = resolve_precision_tier(None)
        variant, tile = conv_norm_plan(
            h, w, c, k, int(self.filters.shape[0]),
            allow_sweep=not has_tracers(imgs), tier=tier,
        )
        if tile is None:
            return None
        return variant, tile, tier

    def _apply_batch_pallas(self, imgs, variant: str, tile_f: int,
                            tier: str = "f32"):
        """Fused kernel path: one HBM read of each image, im2col matmul +
        patch statistics + normalization + whitener shift all in VMEM
        (``ops/pallas/extraction.py::conv_norm``) — no raw/s1/s2
        intermediates. Parity with the XLA twin is pinned in
        ``tests/test_pallas_extraction.py``."""
        from keystone_tpu.ops.pallas.extraction import conv_norm

        return conv_norm(
            imgs,
            self.filters,
            num_channels=self.num_channels,
            normalize=self.normalize_patches,
            var_constant=self.var_constant,
            whitener_means=(
                None if self.whitener is None else self.whitener.means
            ),
            tile_f=tile_f,
            tier=tier,
            variant=variant,
        )

    def _apply_batch_xla(self, imgs):
        k, c = self.conv_size, self.num_channels
        nf = self.filters.shape[0]
        kernel = self.filters.reshape(nf, k, k, c).transpose(1, 2, 3, 0)  # HWIO
        dn = jax.lax.conv_dimension_numbers(
            imgs.shape, kernel.shape, ("NHWC", "HWIO", "NHWC")
        )
        raw = jax.lax.conv_general_dilated(
            imgs, kernel, (1, 1), "VALID", dimension_numbers=dn
        )  # (N, resH, resW, nF)

        out = raw
        if self.normalize_patches:
            n = k * k * c
            ones = jnp.ones((k, k, c, 1), imgs.dtype)
            s1 = jax.lax.conv_general_dilated(
                imgs, ones, (1, 1), "VALID", dimension_numbers=dn
            )
            s2 = jax.lax.conv_general_dilated(
                imgs * imgs, ones, (1, 1), "VALID", dimension_numbers=dn
            )
            mean = s1 / n
            var = (s2 - s1 * mean) / (n - 1.0)
            sd = jnp.sqrt(var + self.var_constant)
            fsum = jnp.sum(self.filters, axis=1)  # (nF,)
            out = (raw - mean * fsum[None, None, None, :]) / sd
        if self.whitener is not None:
            mf = self.whitener.means @ self.filters.T  # (nF,)
            out = out - mf[None, None, None, :]
        return out
