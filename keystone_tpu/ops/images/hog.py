"""Felzenszwalb HOG features (voc-dpm variant).

Reference: ``nodes/images/HogExtractor.scala:33-296`` (itself a port of the
voc-dpm C ``features.cc``): per pixel, the max-gradient color channel is
kept, its orientation snapped to 18 contrast-sensitive bins by maximizing
``uu[o]·dy + vv[o]·dx``; magnitudes are bilinearly binned into binSize cells;
cell energies (over 9 folded orientations) feed four 2×2 block norms; output
per interior cell is 18 contrast-sensitive + 9 insensitive + 4 texture + 1
truncation feature = 32 dims, each clamped at 0.2.

Axis convention: the reference's ``xDim`` IS the image height
(``utils/images/Image.scala:139``), so ref-x is our axis 0 and ref-y our
axis 1 throughout — dx differentiates along the height axis.

Vectorized: the per-pixel loops become one scatter-add; everything else is
slicing arithmetic. Tie-breaking on exactly-equal gradients/dots differs
from the scalar reference in measure-zero cases.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer

_EPSILON = 0.0001

_UU = np.array(
    [1.0, 0.9397, 0.766, 0.5, 0.1736, -0.1736, -0.5, -0.766, -0.9397], np.float32
)
_VV = np.array(
    [0.0, 0.342, 0.6428, 0.866, 0.9848, 0.9848, 0.866, 0.6428, 0.342], np.float32
)


def _round_half_up(v: float) -> int:
    """Scala math.round semantics (Python round() is round-half-even)."""
    return int(math.floor(v + 0.5))


class HogExtractor(Transformer):
    bin_size: int = struct.field(pytree_node=False, default=8)

    def apply(self, img):
        """(H, W, C) -> ((nxc-2)·(nyc-2), 32). Ref-x = axis 0 (height)."""
        h, w, c = img.shape
        nxc = _round_half_up(h / self.bin_size)  # cells along ref-x (height)
        nyc = _round_half_up(w / self.bin_size)
        # the visible region may exceed the image when rounding up; pixels
        # run [1, min(vis, dim) - 1) like the reference's image.get bounds
        vis_x = min(nxc * self.bin_size, h)
        vis_y = min(nyc * self.bin_size, w)

        xs = jnp.arange(1, vis_x - 1)  # ref-x pixel coords (axis 0)
        ys = jnp.arange(1, vis_y - 1)  # ref-y pixel coords (axis 1)
        sub = img[:vis_x, :vis_y, :]
        dx = sub[2:, 1:-1, :] - sub[:-2, 1:-1, :]  # d/d(ref-x), shape (X, Y, C)
        dy = sub[1:-1, 2:, :] - sub[1:-1, :-2, :]
        mag2 = dx * dx + dy * dy
        # max-magnitude channel (ref ties -> highest channel; argmax -> lowest)
        best_c = jnp.argmax(mag2, axis=-1)
        take = lambda a: jnp.take_along_axis(a, best_c[..., None], axis=-1)[..., 0]
        bdx, bdy, bmag2 = take(dx), take(dy), take(mag2)
        magnitude = jnp.sqrt(bmag2)

        # orientation snap: check order o0+, o0-, o1+, o1-, ... first max wins
        dots = bdy[..., None] * _UU[None, None, :] + bdx[..., None] * _VV[None, None, :]
        interleaved = jnp.stack([dots, -dots], axis=-1).reshape(*dots.shape[:-1], 18)
        idx = jnp.argmax(interleaved, axis=-1)
        orientation = idx // 2 + 9 * (idx % 2)  # (X, Y)

        # bilinear binning into cells
        xp = (xs.astype(jnp.float32) + 0.5) / self.bin_size - 0.5
        yp = (ys.astype(jnp.float32) + 0.5) / self.bin_size - 0.5
        ixp = jnp.floor(xp).astype(jnp.int32)
        iyp = jnp.floor(yp).astype(jnp.int32)
        vx0 = xp - ixp
        vy0 = yp - iyp

        hist = jnp.zeros((nxc, nyc, 18), jnp.float32)
        X, Y = magnitude.shape
        ix = jnp.broadcast_to(ixp[:, None], (X, Y))
        iy = jnp.broadcast_to(iyp[None, :], (X, Y))
        wx0 = jnp.broadcast_to(vx0[:, None], (X, Y))
        wy0 = jnp.broadcast_to(vy0[None, :], (X, Y))
        for dxc, dyc, wgt in (
            (0, 0, (1 - wx0) * (1 - wy0)),
            (1, 0, wx0 * (1 - wy0)),
            (0, 1, (1 - wx0) * wy0),
            (1, 1, wx0 * wy0),
        ):
            cx = ix + dxc
            cy = iy + dyc
            ok = (cx >= 0) & (cx < nxc) & (cy >= 0) & (cy < nyc)
            hist = hist.at[
                jnp.where(ok, cx, 0), jnp.where(ok, cy, 0), orientation
            ].add(jnp.where(ok, wgt * magnitude, 0.0))

        # cell energies over folded orientations
        folded = hist[..., :9] + hist[..., 9:]
        norm = jnp.sum(folded * folded, axis=-1)  # (nxc, nyc)

        nxf, nyf = max(nxc - 2, 0), max(nyc - 2, 0)
        if nxf == 0 or nyf == 0:
            return jnp.zeros((0, 32), jnp.float32)

        def bsum(ox, oy):
            b = norm[ox : ox + nxf + 1, oy : oy + nyf + 1]
            return b[:-1, :-1] + b[:-1, 1:] + b[1:, :-1] + b[1:, 1:]

        # reference n1..n4 anchors (HogExtractor.scala:198-212): n1 at
        # (x+1,y+1), n2 at (x,y+1), n3 at (x+1,y), n4 at (x,y)
        n1 = 1.0 / jnp.sqrt(bsum(1, 1) + _EPSILON)
        n2 = 1.0 / jnp.sqrt(bsum(0, 1) + _EPSILON)
        n3 = 1.0 / jnp.sqrt(bsum(1, 0) + _EPSILON)
        n4 = 1.0 / jnp.sqrt(bsum(0, 0) + _EPSILON)
        ns = jnp.stack([n1, n2, n3, n4], axis=-1)  # (nxf, nyf, 4)

        center = hist[1 : 1 + nxf, 1 : 1 + nyf, :]  # (nxf, nyf, 18)
        hsens = jnp.minimum(center[..., None] * ns[..., None, :], 0.2)
        f_sens = 0.5 * jnp.sum(hsens, axis=-1)  # (nxf, nyf, 18)
        csum = center[..., :9] + center[..., 9:]
        hins = jnp.minimum(csum[..., None] * ns[..., None, :], 0.2)
        f_ins = 0.5 * jnp.sum(hins, axis=-1)  # (nxf, nyf, 9)
        f_tex = 0.2357 * jnp.sum(hsens, axis=-2)  # (nxf, nyf, 4)
        f_trunc = jnp.zeros((nxf, nyf, 1), jnp.float32)

        feats = jnp.concatenate([f_sens, f_ins, f_tex, f_trunc], axis=-1)
        # reference row order: y + x*numYCellsWithFeatures (ref-x major) —
        # with ref-x = axis 0 that is a plain row-major reshape
        return feats.reshape(nxf * nyf, 32)
