"""Windower: flat-map of all (stride, window_size) patches of each image.

Reference: ``nodes/images/Windower.scala:13-56`` (an ``RDD[Image] =>
RDD[Image]`` FunctionNode). Batch shape (N, H, W, C) ->
(N·ny·nx, ws, ws, C) via ``conv_general_dilated_patches`` — one XLA op, no
python loop over windows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import FunctionNode


class Windower(FunctionNode):
    stride: int = struct.field(pytree_node=False)
    window_size: int = struct.field(pytree_node=False)

    def apply_batch(self, imgs):
        n, h, w, c = imgs.shape
        ws = self.window_size
        patches = jax.lax.conv_general_dilated_patches(
            imgs,
            filter_shape=(ws, ws),
            window_strides=(self.stride, self.stride),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # (N, ny, nx, C*ws*ws) with feature axis ordered (C, wy, wx)
        ny, nx = patches.shape[1], patches.shape[2]
        patches = patches.reshape(n * ny * nx, c, ws, ws)
        return patches.transpose(0, 2, 3, 1)  # back to (windows, ws, ws, C)
