"""Strided pooling.

Reference: ``nodes/images/Pooler.scala:20-68`` — pools of ``pool_size`` at
strides starting from ``pool_size/2``, a ``pixel_function`` pre-map and a
pooling aggregator; windows at the right/bottom edge are clamped to the
image. Maps to ``lax.reduce_window`` with asymmetric padding supplying the
clamped windows (identity element padding keeps them exact).
"""

from __future__ import annotations

from typing import Callable, ClassVar, Optional

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer


def _pool_geometry(dim: int, stride: int, pool_size: int) -> tuple[int, int]:
    """Returns (num_pools, right_pad) for one spatial dim."""
    stride_start = pool_size // 2
    num_pools = -(-(dim - stride_start) // stride)  # ceil
    # window i covers [i*stride, i*stride + pool_size); pad to reach the last
    last_end = (num_pools - 1) * stride + pool_size
    return num_pools, max(0, last_end - dim)


class Pooler(Transformer):
    stride: int = struct.field(pytree_node=False)
    pool_size: int = struct.field(pytree_node=False)
    pixel_function: Optional[Callable] = struct.field(pytree_node=False, default=None)
    pool: str = struct.field(pytree_node=False, default="sum")  # sum | max

    def _pallas_ok(self, img) -> bool:
        """Fused Pallas sum-pool eligibility: explicit-grade knob
        (``KEYSTONE_PALLAS=1``), sum pooling only (max is not a selection
        matmul — it stays on the ``reduce_window`` twin), float32 input
        (the kernel computes in f32; any other dtype — uint8 wrap-around
        sums, f64 — must keep the twin's exact semantics), and a pixel
        function that is shape/dtype-preserving (``eval_shape`` probe; the
        kernel hands such a function the full untiled channel block, so
        channel-mixing functions stay correct — which also means the FULL
        (H, W, C) block must fit the VMEM budget, since the channel axis
        cannot be tiled under it)."""
        from keystone_tpu.ops.pallas.extraction import (
            pallas_enabled,
            pool_block_fits,
        )

        if self.pool != "sum" or not pallas_enabled(auto_ok=False):
            return False
        if img.dtype != jnp.float32:
            return False
        if self.pixel_function is not None:
            h, w, c = int(img.shape[0]), int(img.shape[1]), int(img.shape[2])
            if not pool_block_fits(h, w, c):
                return False
            try:
                spec = jax.eval_shape(
                    self.pixel_function,
                    jax.ShapeDtypeStruct(img.shape, jnp.float32),
                )
            except Exception:
                return False
            if spec.shape != tuple(img.shape) or spec.dtype != jnp.float32:
                return False
        return True

    def _pallas_plan_for(self, imgs):
        """``(variant, tile_c)`` when the fused kernel should run on this
        (N, H, W, C) batch, else None (the XLA twin). The single decision
        point for both ``apply`` and ``apply_batch`` — ``apply`` must not
        route through ``apply_batch``'s fallback (the inherited twin is
        vmap-of-apply; a shared fallback would recurse). The contraction-
        order variant is the autotuner's measured winner
        (``pool_sum_plan``)."""
        if imgs.ndim != 4 or not self._pallas_ok(imgs[0]):
            return None
        from keystone_tpu.core.cache import has_tracers
        from keystone_tpu.ops.pallas.extraction import pool_sum_plan

        h, w, c = int(imgs.shape[1]), int(imgs.shape[2]), int(imgs.shape[3])
        if self.pixel_function is not None:
            # untiled full channel block (budget-checked in _pallas_ok) —
            # resolving a channel tile here would be a wasted lookup; the
            # hand-written contraction order rides along
            return "hw", c
        variant, tile = pool_sum_plan(
            h, w, c, stride=self.stride, pool_size=self.pool_size,
            allow_sweep=not has_tracers(imgs),
        )
        return None if tile is None else (variant, tile)

    def _pallas_batch(self, imgs, variant: str, tile_c: int):
        from keystone_tpu.ops.pallas.extraction import pool_sum

        return pool_sum(
            imgs, self.stride, self.pool_size, self.pixel_function,
            tile_c=tile_c, variant=variant,
        )

    def apply(self, img):
        plan = self._pallas_plan_for(img[None]) if img.ndim == 3 else None
        if plan is not None:
            return self._pallas_batch(img[None], *plan)[0]
        return self._apply_xla(img)

    def apply_batch(self, imgs):
        """Batch path: the fused Pallas kernel when eligible
        (pixel-function + both selection matmuls in VMEM, see
        ``ops/pallas/extraction.py::pool_sum``), else the inherited
        vmap-of-apply twin — byte-identical to the pre-kernel behavior."""
        plan = self._pallas_plan_for(imgs)
        if plan is not None:
            return self._pallas_batch(imgs, *plan)
        return Transformer.apply_batch(self, imgs)

    def _apply_xla(self, img):
        h, w, c = img.shape
        if self.pixel_function is not None:
            img = self.pixel_function(img)
        (ph, pad_h) = _pool_geometry(h, self.stride, self.pool_size)
        (pw, pad_w) = _pool_geometry(w, self.stride, self.pool_size)
        if self.pool == "sum":
            init, op = 0.0, jax.lax.add
        elif self.pool == "max":
            init, op = -jnp.inf, jax.lax.max
        else:
            raise ValueError(f"unknown pool {self.pool!r}")
        out = jax.lax.reduce_window(
            img,
            jnp.asarray(init, img.dtype),
            op,
            window_dimensions=(self.pool_size, self.pool_size, 1),
            window_strides=(self.stride, self.stride, 1),
            padding=((0, pad_h), (0, pad_w), (0, 0)),
        )
        assert out.shape == (ph, pw, c), (out.shape, ph, pw, c)
        return out
