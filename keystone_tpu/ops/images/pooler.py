"""Strided pooling.

Reference: ``nodes/images/Pooler.scala:20-68`` — pools of ``pool_size`` at
strides starting from ``pool_size/2``, a ``pixel_function`` pre-map and a
pooling aggregator; windows at the right/bottom edge are clamped to the
image. Maps to ``lax.reduce_window`` with asymmetric padding supplying the
clamped windows (identity element padding keeps them exact).
"""

from __future__ import annotations

from typing import Callable, ClassVar, Optional

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import Transformer


def _pool_geometry(dim: int, stride: int, pool_size: int) -> tuple[int, int]:
    """Returns (num_pools, right_pad) for one spatial dim."""
    stride_start = pool_size // 2
    num_pools = -(-(dim - stride_start) // stride)  # ceil
    # window i covers [i*stride, i*stride + pool_size); pad to reach the last
    last_end = (num_pools - 1) * stride + pool_size
    return num_pools, max(0, last_end - dim)


class Pooler(Transformer):
    stride: int = struct.field(pytree_node=False)
    pool_size: int = struct.field(pytree_node=False)
    pixel_function: Optional[Callable] = struct.field(pytree_node=False, default=None)
    pool: str = struct.field(pytree_node=False, default="sum")  # sum | max

    def apply(self, img):
        h, w, c = img.shape
        if self.pixel_function is not None:
            img = self.pixel_function(img)
        (ph, pad_h) = _pool_geometry(h, self.stride, self.pool_size)
        (pw, pad_w) = _pool_geometry(w, self.stride, self.pool_size)
        if self.pool == "sum":
            init, op = 0.0, jax.lax.add
        elif self.pool == "max":
            init, op = -jnp.inf, jax.lax.max
        else:
            raise ValueError(f"unknown pool {self.pool!r}")
        out = jax.lax.reduce_window(
            img,
            jnp.asarray(init, img.dtype),
            op,
            window_dimensions=(self.pool_size, self.pool_size, 1),
            window_strides=(self.stride, self.stride, 1),
            padding=((0, pad_h), (0, pad_w), (0, 0)),
        )
        assert out.shape == (ph, pw, c), (out.shape, ph, pw, c)
        return out
