from keystone_tpu.ops.stats.nodes import (
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    BatchSignedHellingerMapper,
)
from keystone_tpu.ops.stats.scaler import StandardScaler, StandardScalerModel
