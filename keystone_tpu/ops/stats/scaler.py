"""StandardScaler: per-feature mean/std normalization.

Reference: ``nodes/stats/StandardScaler.scala:16-60`` — mean/variance via a
``treeAggregate`` of Spark's ``MultivariateOnlineSummarizer`` (unbiased n-1
variance), model applies ``(x-mean)/std`` with a NaN/eps guard.

TPU-native: the moments are masked sums over the row-sharded batch; under jit
XLA turns them into per-shard partial sums + an ICI all-reduce — the direct
``treeAggregate`` replacement (SURVEY.md §2.13).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.dataset import Dataset
from keystone_tpu.core.pipeline import Estimator, Transformer


class StandardScalerModel(Transformer):
    mean: jax.Array
    std: Optional[jax.Array] = None

    def apply(self, x):
        out = x - self.mean
        if self.std is not None:
            out = out / self.std
        return out

    def apply_batch(self, xs):
        out = xs - self.mean
        if self.std is not None:
            out = out / self.std
        return out


@functools.partial(jax.jit, static_argnames=("use_std",))
def _fit_moments(xs, mask, use_std: bool):
    xs = xs.astype(jnp.float32)
    if mask is None:
        n = jnp.float32(xs.shape[0])
        sum_x = jnp.sum(xs, axis=0)
        mean = sum_x / n
        if not use_std:
            return mean, None
        var = jnp.sum((xs - mean) ** 2, axis=0) / jnp.maximum(n - 1.0, 1.0)
    else:
        n = jnp.sum(mask)
        mean = jnp.sum(xs * mask[:, None], axis=0) / n
        if not use_std:
            return mean, None
        var = jnp.sum(mask[:, None] * (xs - mean) ** 2, axis=0) / jnp.maximum(
            n - 1.0, 1.0
        )
    std = jnp.sqrt(var)
    # eps/NaN guard (reference ``StandardScaler.scala:25-31``): constant
    # features pass through as zeros rather than NaNs.
    std = jnp.where(jnp.isfinite(std) & (std > 1e-12), std, 1.0)
    return mean, std


class StandardScaler(Estimator):
    """Reference: ``nodes/stats/StandardScaler.scala:39-60``.

    ``normalize_std_dev=False`` is the centering-only mode the linear solvers
    use (``nodes/learning/LinearMapper.scala:78-79``).
    """

    def __init__(self, normalize_std_dev: bool = True):
        self.normalize_std_dev = normalize_std_dev

    def fit(self, data, mask: Optional[jax.Array] = None) -> StandardScalerModel:
        if isinstance(data, Dataset):
            data, mask = data.data, data.mask if mask is None else mask
        mean, std = _fit_moments(data, mask, self.normalize_std_dev)
        return StandardScalerModel(mean=mean, std=std)


@functools.partial(jax.jit, static_argnames=("size",))
def _scaler_chunk_accum(node, raw, mask, acc, start, size):
    import jax.lax as lax

    rc = jax.tree.map(lambda a: lax.dynamic_slice_in_dim(a, start, size, 0), raw)
    f = node.apply_batch(rc).astype(jnp.float32)
    if mask is not None:
        mc = lax.dynamic_slice_in_dim(mask, start, size, 0)
        f = f * mc[:, None]
    s, s2 = acc
    return s + jnp.sum(f, axis=0), s2 + jnp.sum(f * f, axis=0)


def fit_node_scaler_chunked(
    node,
    raw,
    mask: Optional[jax.Array] = None,
    chunk: int = 1 << 17,
    normalize_std_dev: bool = True,
) -> StandardScalerModel:
    """Fit a :class:`StandardScalerModel` over ``node(raw)`` WITHOUT ever
    materializing the full (n, b) feature batch: Σf and Σf² accumulate over
    row chunks and the unbiased moments follow in closed form
    (``var = (Σf² − n·mean²)/(n−1)``, same eps/NaN guard as
    ``StandardScaler``). This is how per-batch feature scalers fit at
    full-TIMIT scale, where one 4096-wide feature batch of 2.2M rows is
    36 GB (``TimitPipeline.scala:81``'s per-batch scaler, out-of-core).
    Exact equivalence with the in-core fit pinned in
    ``tests/test_block_linear_streaming.py``.
    """
    n = jax.tree.leaves(raw)[0].shape[0]
    probe = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct((min(chunk, n),) + a.shape[1:], a.dtype),
        raw,
    )
    b = jax.eval_shape(node.apply_batch, probe).shape[1]
    acc = (jnp.zeros((b,), jnp.float32), jnp.zeros((b,), jnp.float32))
    for start in range(0, n, chunk):
        acc = _scaler_chunk_accum(
            node, raw, mask, acc, jnp.int32(start), min(chunk, n - start)
        )
    s, s2 = acc
    n_eff = jnp.sum(mask) if mask is not None else jnp.float32(n)
    mean = s / n_eff
    if not normalize_std_dev:
        return StandardScalerModel(mean=mean, std=None)
    var = (s2 - n_eff * mean * mean) / jnp.maximum(n_eff - 1.0, 1.0)
    std = jnp.sqrt(var)
    std = jnp.where(jnp.isfinite(std) & (std > 1e-12), std, 1.0)
    return StandardScalerModel(mean=mean, std=std)
