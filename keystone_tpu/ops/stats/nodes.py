"""Stats nodes. Reference: ``src/main/scala/nodes/stats/`` (271 LoC).

All of these are elementwise / per-item maps or single gemms — exactly the
ops XLA fuses into neighbouring matmuls, so each is written as the obvious
jnp expression and batching is one fused program, not N small kernels.
"""

from __future__ import annotations

import math
from typing import ClassVar, Optional

import jax
import jax.numpy as jnp
import numpy as np
import flax.struct as struct

from keystone_tpu.core.pipeline import FunctionNode, Transformer


class LinearRectifier(Transformer):
    """``max(max_val, x - alpha)``. Reference: ``nodes/stats/LinearRectifier.scala:11-16``."""

    max_val: float = struct.field(pytree_node=False, default=0.0)
    alpha: float = struct.field(pytree_node=False, default=0.0)

    def apply(self, x):
        return jnp.maximum(self.max_val, x - self.alpha)


class RandomSignNode(Transformer):
    """Elementwise multiply by a fixed ±1 sign vector.

    Reference: ``nodes/stats/RandomSignNode.scala:11-24``.
    """

    signs: jax.Array

    def __contract__(self):
        from keystone_tpu.analysis import contracts as C

        d = int(self.signs.shape[0])
        return C.NodeContract(
            accepts=lambda a: C.expect_last_dim(
                a, d, "the sign-vector width"
            ),
            in_template=lambda: C.spec_struct(1, d),
        )

    def apply(self, x):
        return x * self.signs

    @staticmethod
    def create(num_features: int, key: jax.Array) -> "RandomSignNode":
        signs = jax.random.bernoulli(key, 0.5, (num_features,))
        return RandomSignNode(signs=jnp.where(signs, 1.0, -1.0).astype(jnp.float32))


class NormalizeRows(Transformer):
    """L2-normalize with an epsilon floor.

    Reference: ``nodes/stats/NormalizeRows.scala:10-14`` —
    ``x / max(‖x‖₂, 2.2e-16)``.
    """

    def apply(self, x):
        return x / jnp.maximum(jnp.linalg.norm(x), 2.2e-16)


class SignedHellingerMapper(Transformer):
    """``sign(x)·√|x|``. Reference: ``nodes/stats/SignedHellingerMapper.scala:12-16``."""

    def apply(self, x):
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


# The reference needed a separate Float-matrix batch variant
# (``SignedHellingerMapper.scala:18-22``); here the same node works on any
# shape, but the alias keeps the inventory 1:1.
BatchSignedHellingerMapper = SignedHellingerMapper


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class PaddedFFT(Transformer):
    """Zero-pad to the next power of two, FFT, keep real parts of the first
    half. 784 -> 512 for MNIST. Reference: ``nodes/stats/PaddedFFT.scala:13-21``.

    Uses ``jnp.fft.rfft`` (the first ``n/2`` complex bins of the full FFT),
    which XLA lowers to the TPU's FFT implementation — this replaces the
    reference's breeze/JTransforms host FFT.
    """

    def apply(self, x):
        n = _next_pow2(x.shape[0])
        return jnp.fft.rfft(x, n=n).real[: n // 2].astype(jnp.float32)


class CosineRandomFeatures(Transformer):
    """Random Fourier features: ``cos(x·Wᵀ + b)``.

    Reference: ``nodes/stats/CosineRandomFeatures.scala:18-57``. The batch
    path is one ``(n,d)×(d,D)`` gemm — MXU-shaped by construction (the
    reference hand-batched each partition for the same reason, ``:24-32``).
    """

    w: jax.Array  # (num_output, num_input)
    b: jax.Array  # (num_output,)

    def __contract__(self):
        from keystone_tpu.analysis import contracts as C

        d = int(self.w.shape[1])
        return C.NodeContract(
            accepts=lambda a: (
                C.expect_rank(a, (2,), "feature batch (n, d)")
                or C.expect_last_dim(a, d, "the random-feature input dim")
            ),
            in_template=lambda: C.spec_struct(1, d),
        )

    def apply(self, x):
        return jnp.cos(x @ self.w.T + self.b)

    def apply_batch(self, xs):
        return jnp.cos(xs @ self.w.T + self.b)

    @staticmethod
    def create(
        num_input: int,
        num_output: int,
        gamma: float,
        key: jax.Array,
        distribution: str = "gaussian",
    ) -> "CosineRandomFeatures":
        """W ~ gaussian|cauchy scaled by gamma, b ~ U[0, 2π).

        Reference companion: ``CosineRandomFeatures.scala:45-56``.
        """
        kw, kb = jax.random.split(key)
        if distribution == "gaussian":
            w = jax.random.normal(kw, (num_output, num_input), jnp.float32)
        elif distribution == "cauchy":
            u = jax.random.uniform(kw, (num_output, num_input), jnp.float32)
            w = jnp.tan(jnp.pi * (u - 0.5))
        else:
            raise ValueError(f"unknown distribution {distribution!r}")
        b = jax.random.uniform(kb, (num_output,), jnp.float32, 0.0, 2.0 * math.pi)
        return CosineRandomFeatures(w=w * gamma, b=b)


class ColumnSampler(FunctionNode):
    """Sample descriptors across a batch of per-item descriptor sets.

    Reference: ``nodes/stats/Sampling.scala:11-29`` (samples columns of an
    RDD of descriptor matrices). Here items are (n_items, n_desc, d): the
    sample is over the flattened descriptor axis.
    """

    jittable: ClassVar[bool] = False
    num_samples: int = struct.field(pytree_node=False)
    seed: int = struct.field(pytree_node=False, default=42)

    def __contract__(self):
        """Host node with a DECLARED abstract transfer: the sample size is
        min(num_samples, total descriptors) — data-independent, so the
        checker's propagation (and the planner's cost table) see through
        what ``jax.eval_shape`` cannot."""
        from keystone_tpu.analysis import contracts as C

        def out(a):
            leaf = C.leading_leaf(a)
            total = 1
            for s in leaf.shape[:-1]:
                total *= int(s)
            return C.spec_struct(
                min(int(self.num_samples), total), int(leaf.shape[-1]),
                dtype=leaf.dtype,
            )

        return C.NodeContract(
            accepts=lambda a: C.expect_rank(
                a, (2, 3), "descriptor batch (n[, n_desc], d)"
            ),
            out=out,
        )

    def apply_batch(self, descs):
        if isinstance(descs, jax.Array):
            # Stay on device: pulling a (n·n_desc, d) descriptor tensor to the
            # host just to subsample costs minutes over a tunneled link.
            flat = descs.reshape(-1, descs.shape[-1])
        else:
            flat = np.asarray(descs).reshape(-1, descs.shape[-1])
        return jnp.asarray(
            Sampler(size=self.num_samples, seed=self.seed).apply_batch(flat)
        )


class Sampler(FunctionNode):
    """Uniform row sample without replacement (host-side, concrete sizes).

    Reference: ``nodes/stats/Sampling.scala:33-37`` (``takeSample`` with
    ``seed=42``).

    RNG note: the sample indices come from ``jax.random`` for device-resident
    inputs and from numpy's Generator for host arrays — the same seed picks a
    *different* (deterministic) subset on the two paths. Real-pipeline
    descriptors are device arrays, so fits are reproducible run-to-run; only
    code that moves the same data between host and device sees a different
    (equally uniform) sample. Applies to :class:`ColumnSampler` too.
    """

    jittable: ClassVar[bool] = False
    size: int = struct.field(pytree_node=False)
    seed: int = struct.field(pytree_node=False, default=42)

    def __contract__(self):
        from keystone_tpu.analysis import contracts as C

        def out(a):
            leaf = C.leading_leaf(a)
            return C.spec_struct(
                min(int(self.size), int(leaf.shape[0])), *leaf.shape[1:],
                dtype=leaf.dtype,
            )

        return C.NodeContract(
            accepts=lambda a: C.expect_rank(a, (2,), "row batch (n, d)"),
            out=out,
        )

    def apply_batch(self, xs):
        n = xs.shape[0]
        take = min(self.size, n)
        if isinstance(xs, jax.Array):
            # Device-side sample — no host round-trip for device-resident data.
            idx = jax.random.choice(
                jax.random.key(self.seed), n, (take,), replace=False
            )
            return jnp.take(xs, jnp.sort(idx), axis=0)
        idx = np.random.default_rng(self.seed).choice(n, size=take, replace=False)
        return xs[np.sort(idx)]
