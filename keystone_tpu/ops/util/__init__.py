from keystone_tpu.ops.util.nodes import (
    Cast,
    ClassLabelIndicatorsFromIntLabels,
    ClassLabelIndicatorsFromIntArrayLabels,
    FloatToDouble,
    MatrixVectorizer,
    MaxClassifier,
    TopKClassifier,
    VectorSplitter,
    ZipVectors,
)
