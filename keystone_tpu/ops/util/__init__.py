from keystone_tpu.ops.util.nodes import (
    Cast,
    ClassLabelIndicatorsFromIntLabels,
    ClassLabelIndicatorsFromIntArrayLabels,
    FloatToDouble,
    MatrixVectorizer,
    MaxClassifier,
    TopKClassifier,
    VectorSplitter,
    ZipVectors,
)
from keystone_tpu.ops.util.sparse import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseBatch,
    SparseFeatureVectorizer,
    TermFrequency,
)
