"""Utility nodes. Reference: ``src/main/scala/nodes/util/`` (236 LoC).

``Cacher`` and ``Identity`` live in :mod:`keystone_tpu.core.pipeline`.
Sparse-feature nodes live in :mod:`keystone_tpu.ops.util.sparse`.
"""

from __future__ import annotations

from typing import Any, ClassVar, Optional, Sequence

import jax
import jax.numpy as jnp
import flax.struct as struct

from keystone_tpu.core.pipeline import FunctionNode, Transformer


class ClassLabelIndicatorsFromIntLabels(Transformer):
    """Int class label -> ±1 indicator vector.

    Reference: ``nodes/util/ClassLabelIndicators.scala:11-20``.
    """

    num_classes: int = struct.field(pytree_node=False)

    def apply(self, label):
        return jnp.where(
            jnp.arange(self.num_classes) == label, 1.0, -1.0
        ).astype(jnp.float32)


class ClassLabelIndicatorsFromIntArrayLabels(Transformer):
    """Multi-label int array -> ±1 indicator vector.

    Labels are a fixed-width int array padded with -1 (XLA static shapes
    replace the reference's ragged ``Array[Int]``,
    ``nodes/util/ClassLabelIndicators.scala:24-36``).
    """

    num_classes: int = struct.field(pytree_node=False)

    def apply(self, labels):
        classes = jnp.arange(self.num_classes)
        hit = jnp.any(labels[:, None] == classes[None, :], axis=0)
        return jnp.where(hit, 1.0, -1.0).astype(jnp.float32)


class MaxClassifier(Transformer):
    """argmax over scores. Reference: ``nodes/util/MaxClassifier.scala:8-10``."""

    def apply(self, x):
        return jnp.argmax(x)


class TopKClassifier(Transformer):
    """Top-k class indices, best first.

    Reference: ``nodes/util/TopKClassifier.scala:8-16`` (breeze ``argtopk``).
    """

    k: int = struct.field(pytree_node=False)

    def apply(self, x):
        _, idx = jax.lax.top_k(x, self.k)
        return idx


class VectorSplitter(FunctionNode):
    """Split the feature axis into column blocks of ``block_size`` — the
    model-parallel splitter feeding the block solvers.

    Reference: ``nodes/util/VectorSplitter.scala:10-34``. The TPU-native block
    solvers (:mod:`keystone_tpu.learning.block_linear`) usually slice
    internally instead; this node exists for pipeline-level blocking (e.g.
    zipping per-FFT feature groups in MnistRandomFFT).
    """

    block_size: int = struct.field(pytree_node=False)

    def apply_batch(self, xs) -> tuple:
        d = xs.shape[1]
        return tuple(
            xs[:, i : min(i + self.block_size, d)]
            for i in range(0, d, self.block_size)
        )


class ZipVectors(FunctionNode):
    """Concatenate a sequence of co-sharded feature blocks back into one
    feature matrix. Reference: ``nodes/util/ZipVectors.scala:10-14`` (zip +
    vertcat of co-partitioned RDDs -> same-shard concat on the feature axis).
    """

    def apply_batch(self, blocks: Sequence[Any]):
        return jnp.concatenate(list(blocks), axis=1)


class MatrixVectorizer(Transformer):
    """Flatten a matrix to a vector, column-major to match Breeze's
    ``toDenseVector``. Reference: ``nodes/util/MatrixVectorizer.scala:9-11``.
    """

    def apply(self, x):
        return x.T.reshape(-1)


class Cast(Transformer):
    """dtype cast. Stands in for the reference's ``FloatToDouble``
    (``nodes/util/FloatToDouble.scala:9-11``): TPUs have no fast float64, so
    solver precision comes from float32 + ``Precision.HIGHEST`` matmuls
    instead of widening the element type.
    """

    dtype: Any = struct.field(pytree_node=False)

    def apply(self, x):
        return x.astype(self.dtype)


def FloatToDouble() -> Cast:
    """Reference-named alias: on TPU this is a float32 cast (see Cast)."""
    return Cast(dtype=jnp.float32)
