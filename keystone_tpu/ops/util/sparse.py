"""Sparse featurization: term frequencies -> padded-COO device batches.

Reference:
- ``nodes/stats/TermFrequency.scala:18-20``: ``Seq[T] -> Seq[(T, weight(count))]``.
- ``nodes/util/AllSparseFeatures.scala:13-19``: feature space = every term seen.
- ``nodes/util/CommonSparseFeatures.scala:15-26``: feature space = top-K terms
  by total frequency.
- ``nodes/util/SparseFeatureVectorizer.scala:7-18``: map per-doc term weights
  into sparse vectors over the fitted feature space.

TPU-native representation: a :class:`SparseBatch` — padded COO with a static
``max_nnz`` per row (indices int32 padded with -1, values float32 padded with
0). Static shapes are what XLA needs; the pad/mask convention matches the rest
of the data plane. Consumers either scatter into dense (vocab fits HBM) or
gather per-row (``NaiveBayesModel.apply_batch``).
"""

from __future__ import annotations

import collections
from typing import Callable, ClassVar, Dict, List, Optional, Sequence, Tuple

import flax.struct as struct
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer


def identity_weight(count: float) -> float:
    """Raw-count term weighting."""
    return float(count)


def binary_weight(count: float) -> float:
    """Presence/absence weighting (the reference pipeline's ``x => 1``)."""
    return 1.0


class TermFrequency(Transformer):
    """Per-doc term counts re-weighted by ``fn`` (``TermFrequency.scala:18-20``).

    ``fn`` maps the raw count to a weight (:func:`identity_weight`,
    :func:`binary_weight`, log-scaling, ...). Use module-level functions, not
    lambdas, so fitted pipelines stay checkpointable (``core/checkpoint.py``).
    """

    jittable: ClassVar[bool] = False
    fn: Callable[[float], float] = struct.field(
        pytree_node=False, default=identity_weight
    )

    def apply(self, terms: Sequence) -> List[Tuple[object, float]]:
        counts = collections.Counter(terms)
        return [(t, self.fn(c)) for t, c in counts.items()]

    def apply_batch(self, docs) -> List[List[Tuple[object, float]]]:
        return [self.apply(d) for d in docs]


class SparseBatch(struct.PyTreeNode):
    """Padded-COO batch: ``indices`` (n, max_nnz) int32 (-1 = pad),
    ``values`` (n, max_nnz) float32, plus the static feature-space size."""

    indices: jnp.ndarray
    values: jnp.ndarray
    num_features: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    def to_dense(self) -> jnp.ndarray:
        """Scatter to (n, num_features) — for feature spaces that fit HBM."""
        idx = jnp.clip(self.indices, 0, self.num_features - 1)
        mask = (self.indices >= 0).astype(self.values.dtype)
        n = self.indices.shape[0]
        dense = jnp.zeros((n, self.num_features), self.values.dtype)
        rows = jnp.arange(n)[:, None]
        return dense.at[rows, idx].add(self.values * mask)


class SparseFeatureVectorizer(Transformer):
    """Vectorize per-doc ``(term, weight)`` lists over a fitted feature map
    (``SparseFeatureVectorizer.scala:7-18``). Unknown terms are dropped."""

    jittable: ClassVar[bool] = False
    feature_index: Dict[object, int] = struct.field(pytree_node=False)

    @property
    def num_features(self) -> int:
        return len(self.feature_index)

    def apply_batch(self, docs: Sequence[Sequence[Tuple[object, float]]]) -> SparseBatch:
        fi = self.feature_index
        per_doc: List[List[Tuple[int, float]]] = []
        for doc in docs:
            row = [(fi[t], w) for t, w in doc if t in fi]
            row.sort()
            per_doc.append(row)
        max_nnz = max(1, max((len(r) for r in per_doc), default=1))
        n = len(per_doc)
        indices = np.full((n, max_nnz), -1, np.int32)
        values = np.zeros((n, max_nnz), np.float32)
        for i, row in enumerate(per_doc):
            for j, (idx, w) in enumerate(row):
                indices[i, j] = idx
                values[i, j] = w
        return SparseBatch(
            indices=jnp.asarray(indices),
            values=jnp.asarray(values),
            num_features=len(fi),
        )

    def apply(self, doc: Sequence[Tuple[object, float]]) -> SparseBatch:
        return self.apply_batch([doc])


class AllSparseFeatures(Estimator):
    """Feature space = every term observed (``AllSparseFeatures.scala:13-19``)."""

    def fit(self, docs: Sequence[Sequence[Tuple[object, float]]]) -> SparseFeatureVectorizer:
        seen: Dict[object, int] = {}
        for doc in docs:
            for t, _ in doc:
                if t not in seen:
                    seen[t] = len(seen)
        return SparseFeatureVectorizer(feature_index=seen)


class CommonSparseFeatures(Estimator):
    """Feature space = top-``num_features`` terms by total weight across the
    corpus (``CommonSparseFeatures.scala:15-26``)."""

    def __init__(self, num_features: int):
        self.num_features = int(num_features)

    def fit(self, docs: Sequence[Sequence[Tuple[object, float]]]) -> SparseFeatureVectorizer:
        totals: collections.Counter = collections.Counter()
        for doc in docs:
            for t, w in doc:
                totals[t] += w
        top = [t for t, _ in totals.most_common(self.num_features)]
        return SparseFeatureVectorizer(feature_index={t: i for i, t in enumerate(top)})
