"""Pipeline launcher: ``python -m keystone_tpu.cli <Pipeline> [flags]``.

Reference: ``bin/run-pipeline.sh:9-28`` — one entry point that dispatches to a
pipeline class by name and forwards flags (there via spark-submit; here the
"cluster config" is the TPU mesh, picked up from the environment by
``keystone_tpu.parallel``).
"""

from __future__ import annotations

import sys

PIPELINES = {
    "MnistRandomFFT": "keystone_tpu.pipelines.mnist_random_fft",
    "LinearPixels": "keystone_tpu.pipelines.linear_pixels",
    "RandomCifar": "keystone_tpu.pipelines.random_cifar",
    "RandomPatchCifar": "keystone_tpu.pipelines.random_patch_cifar",
    "Timit": "keystone_tpu.pipelines.timit",
    "VOCSIFTFisher": "keystone_tpu.pipelines.voc_sift_fisher",
    "ImageNetSiftLcsFV": "keystone_tpu.pipelines.imagenet_sift_lcs_fv",
    "Newsgroups": "keystone_tpu.pipelines.newsgroups",
    "StupidBackoff": "keystone_tpu.pipelines.stupid_backoff",
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        names = "\n  ".join(sorted(PIPELINES))
        print(f"usage: run-pipeline <Pipeline> [flags]\n\npipelines:\n  {names}")
        return 0 if argv else 2
    name, rest = argv[0], argv[1:]
    if name not in PIPELINES:
        # accept snake_case / lowercase spellings: mnist_random_fft == MnistRandomFFT
        canon = {k.replace("_", "").lower(): k for k in PIPELINES}
        name = canon.get(name.replace("_", "").replace("-", "").lower(), name)
    if name not in PIPELINES:
        print(f"unknown pipeline {name!r}; run with --help for the list", file=sys.stderr)
        return 2
    import importlib

    mod = importlib.import_module(PIPELINES[name])
    mod.main(rest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
