"""Pipeline launcher: ``python -m keystone_tpu.cli <Pipeline> [flags]``.

Reference: ``bin/run-pipeline.sh:9-28`` — one entry point that dispatches to a
pipeline class by name and forwards flags (there via spark-submit; here the
"cluster config" is the TPU mesh, picked up from the environment by
``keystone_tpu.parallel``).

Multi-host launch (the ``keystone-ec2.sh`` analog — reference
``bin/keystone-ec2.sh``): instead of provisioning a Spark cluster, every host
of a TPU pod slice runs the same command with

    run-pipeline --coordinator host0:8476 --num-processes N --process-id I \
                 [--mesh-model M] <Pipeline> [flags]

which calls ``jax.distributed.initialize`` before any backend use; after
initialization ``jax.devices()`` is the global device set, so the default
``(data, model)`` mesh — and therefore every sharded gram/psum in the
solvers — spans the whole slice (ICI intra-slice, DCN across slices). On
Cloud TPU metadata-provisioned VMs all three flags may be omitted
(``jax.distributed.initialize()`` auto-detects). ``--mesh-model M`` sets the
model-parallel axis of the default mesh (data axis = n_devices / M).
"""

from __future__ import annotations

import argparse
import sys

PIPELINES = {
    "MnistRandomFFT": "keystone_tpu.pipelines.mnist_random_fft",
    "LinearPixels": "keystone_tpu.pipelines.linear_pixels",
    "RandomCifar": "keystone_tpu.pipelines.random_cifar",
    "RandomPatchCifar": "keystone_tpu.pipelines.random_patch_cifar",
    "Timit": "keystone_tpu.pipelines.timit",
    "VOCSIFTFisher": "keystone_tpu.pipelines.voc_sift_fisher",
    "ImageNetSiftLcsFV": "keystone_tpu.pipelines.imagenet_sift_lcs_fv",
    "Newsgroups": "keystone_tpu.pipelines.newsgroups",
    "StupidBackoff": "keystone_tpu.pipelines.stupid_backoff",
}


def _parse_launch_flags(argv):
    """Split cluster-launch flags (ours) from pipeline flags (forwarded)."""
    # allow_abbrev=False: abbreviated pipeline flags (e.g. --dist...) must
    # reach the pipeline's own parser, not silently become launch flags.
    ap = argparse.ArgumentParser(add_help=False, allow_abbrev=False)
    ap.add_argument("--coordinator", default=None,
                    help="coordinator address host:port for jax.distributed")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--distributed", action="store_true",
                    help="jax.distributed.initialize() with auto-detection")
    ap.add_argument("--mesh-model", type=int, default=1,
                    help="model-parallel axis size of the default mesh")
    ap.add_argument("--hosts", default=None,
                    help="comma-separated host list: print the per-host "
                         "launch commands (coordinator election, process "
                         "ids, mesh shape) instead of running — the "
                         "keystone-ec2.sh analog minus provisioning")
    ap.add_argument("--devices-per-host", type=int, default=4,
                    help="accelerators per host for the --hosts mesh-shape "
                         "note (v5e hosts expose 4)")
    ap.add_argument("--port", type=int, default=8476,
                    help="coordinator port for --hosts")
    return ap.parse_known_args(argv)


def emit_host_commands(hosts, rest, devices_per_host: int = 4,
                       port: int = 8476, mesh_model: int = 1):
    """Per-host launch lines for a multi-controller run (the
    ``bin/keystone-ec2.sh`` analog, ``:9-28`` of the reference launcher,
    minus EC2 provisioning — topology only).

    The first host is elected coordinator; every host gets the same command
    with its own ``--process-id``. Returns (lines, mesh_note)."""
    hosts = [h.strip() for h in hosts if h.strip()]
    if not hosts:
        raise ValueError("--hosts needs at least one host")
    coordinator = f"{hosts[0]}:{port}"
    n = len(hosts)
    total_dev = n * devices_per_host
    model = max(1, mesh_model)
    if total_dev % model:
        raise ValueError(
            f"--mesh-model {model} does not divide the global device count "
            f"{total_dev} ({n} hosts x {devices_per_host})"
        )
    import shlex

    flags = f" --mesh-model {model}" if model > 1 else ""
    pipeline = shlex.join(rest) if rest else "<Pipeline> [flags]"
    lines = [
        (h, f"run-pipeline --coordinator {coordinator} --num-processes {n} "
            f"--process-id {i}{flags} {pipeline}")
        for i, h in enumerate(hosts)
    ]
    mesh_note = (
        f"global mesh: {total_dev} devices -> (data={total_dev // model}, "
        f"model={model}); ICI within each host's slice, DCN across hosts"
    )
    return lines, mesh_note


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Fail-fast env validation: a typo'd KEYSTONE_*/BENCH_* value dies HERE
    # with the knob-named message, instead of being silently ignored (or
    # exploding mid-run at whichever code path first reads it).
    from keystone_tpu.utils import knobs

    try:
        knobs.validate_environment()
    except ValueError as e:
        print(f"invalid environment: {e}", file=sys.stderr)
        return 2
    if argv and argv[0] == "telemetry-report":
        # ``keystone-tpu telemetry-report [path]``: pretty-print a telemetry
        # artifact (bench_telemetry.json / telemetry_metrics.json) — the
        # human half of keystone_tpu/telemetry; no jax import needed.
        from keystone_tpu.telemetry.report import main as report_main

        return report_main(argv[1:])
    if argv and argv[0] == "obs":
        # ``keystone-tpu obs [dir]``: merge the per-process telemetry
        # shards a fleet exported under KEYSTONE_TELEMETRY_DIR into one
        # fleet-wide view (exact counter sums, proc-labeled gauges,
        # unioned histograms, SLO signals) — text/json/prometheus, plus
        # ``--traces`` for the stitched multi-process Perfetto file.
        # No jax import needed.
        from keystone_tpu.telemetry.fleet import obs_main

        return obs_main(argv[1:])
    if argv and argv[0] == "lint":
        # ``keystone-tpu lint [paths]``: the static-analysis pass
        # (keystone_tpu/analysis) — exits non-zero only for findings not
        # in the ratcheted lint_baseline.json.
        from keystone_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "audit":
        # ``keystone-tpu audit [--target X]``: the IR-level static
        # analysis (keystone_tpu/analysis/ir_audit.py) — lowers registered
        # entry points to jaxpr + compiled HLO and runs rules A1-A5; exits
        # non-zero only for findings not in the ratcheted ir_baseline.json.
        # Device request must precede any jax backend use.
        from keystone_tpu.analysis.ir_audit import ensure_cpu_devices
        from keystone_tpu.analysis.ir_audit import main as audit_main

        ensure_cpu_devices()
        return audit_main(argv[1:])
    if argv and argv[0] == "check":
        # ``keystone-tpu check [--target X]``: the construction-time
        # pipeline contract checker (keystone_tpu/analysis/check.py) —
        # propagates (shape, dtype, PartitionSpec) through the registered
        # pipeline graphs pre-dispatch (no data, no compiles) and runs
        # rules C1-C5; exits non-zero only for findings not in the
        # ratcheted check_baseline.json.
        from keystone_tpu.analysis.check import main as check_main

        return check_main(argv[1:])
    if argv and argv[0] == "race":
        # ``keystone-tpu race [paths]``: the lock-discipline static
        # analysis (keystone_tpu/analysis/concurrency.py) — models every
        # lock creation, ``with <lock>:`` span and thread/atexit entry
        # point into an acquisition graph and runs rules T1-T5; exits
        # non-zero only for findings not in the ratcheted
        # race_baseline.json. No jax import needed.
        from keystone_tpu.analysis.concurrency import main as race_main

        return race_main(argv[1:])
    if argv and argv[0] == "plan":
        # ``keystone-tpu plan <target>``: the cost-based whole-pipeline
        # planner's decision table (core/plan.py) — cache tiers, fused
        # segments, sharding boundary, HBM-safe block sizes — plus the
        # exportable JSON artifact via --json.
        from keystone_tpu.core.plan import main as plan_main

        return plan_main(argv[1:])
    if not argv or argv[0] in ("-h", "--help", "help"):
        names = "\n  ".join(sorted(PIPELINES))
        print(
            "usage: run-pipeline [--coordinator HOST:PORT --num-processes N "
            "--process-id I | --distributed] [--mesh-model M] "
            f"<Pipeline> [flags]\n"
            "       run-pipeline telemetry-report [path] [--top N]\n"
            "       run-pipeline obs [dir] [--format text|json|prometheus]"
            " [--traces OUT.json]\n"
            "       run-pipeline lint [paths] [--update-baseline]\n"
            "       run-pipeline audit [--target ENTRY] [--list] "
            "[--update-baseline]\n"
            "       run-pipeline check [--target PIPELINE] [--list] "
            "[--update-baseline]\n"
            "       run-pipeline race [paths] [--update-baseline]\n"
            "       run-pipeline plan <toy|imagenet|voc> [--mode M] "
            "[--budget-mb N] [--json PATH]\n\n"
            f"pipelines:\n  {names}"
        )
        return 0 if argv else 2
    launch, argv = _parse_launch_flags(argv)
    if launch.hosts is not None:
        try:
            lines, mesh_note = emit_host_commands(
                launch.hosts.split(","), argv, launch.devices_per_host,
                launch.port, launch.mesh_model,
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        print(f"# {mesh_note}")
        for host, cmd in lines:
            print(f"{host}: {cmd}")
        return 0
    if (launch.num_processes is not None or launch.process_id is not None) \
            and not (launch.coordinator or launch.distributed):
        print(
            "--num-processes/--process-id require --coordinator (or "
            "--distributed for auto-detection); refusing to run "
            "single-process while the rest of the slice waits at a "
            "collective", file=sys.stderr,
        )
        return 2
    if launch.coordinator or launch.distributed:
        import jax

        kwargs = {}
        if launch.coordinator:
            kwargs = dict(
                coordinator_address=launch.coordinator,
                num_processes=launch.num_processes,
                process_id=launch.process_id,
            )
        jax.distributed.initialize(**kwargs)
    if not argv:
        print("missing pipeline name; run with --help", file=sys.stderr)
        return 2
    name, rest = argv[0], argv[1:]
    if name not in PIPELINES:
        # accept snake_case / lowercase spellings: mnist_random_fft == MnistRandomFFT
        canon = {k.replace("_", "").lower(): k for k in PIPELINES}
        name = canon.get(name.replace("_", "").replace("-", "").lower(), name)
    if name not in PIPELINES:
        print(f"unknown pipeline {name!r}; run with --help for the list", file=sys.stderr)
        return 2
    import importlib

    mod = importlib.import_module(PIPELINES[name])
    if launch.mesh_model > 1:
        import jax

        from keystone_tpu.parallel import make_mesh, use_mesh

        n_dev = len(jax.devices())
        if n_dev % launch.mesh_model:
            print(
                f"--mesh-model {launch.mesh_model} does not divide the "
                f"device count {n_dev}", file=sys.stderr,
            )
            return 2
        with use_mesh(make_mesh(model=launch.mesh_model)):
            mod.main(rest)
    else:
        mod.main(rest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
